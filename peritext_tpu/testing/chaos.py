"""Chaos harness: every fault class composed against the byte-equality oracle.

One :func:`run_chaos` campaign drives a supervised streaming session
(:class:`~..parallel.supervisor.GuardedSession`) through the full fault
space the fault-domain supervisor exists to absorb, in one seeded run:

* **delivery faults** — per-frame drop / duplicate / reorder
  (:class:`~..parallel.faults.FaultSpec`), repaired by redelivery;
* **payload corruption** — truncated / bit-flipped frames
  (:func:`~..parallel.faults.corrupt_detectably`) against a victim subset of
  docs: the codec must reject them (:class:`DecodeError`), the session must
  quarantine exactly those docs with reason ``decode`` and keep the healthy
  docs converging (per-doc fault isolation, checked mid-run);
* **injected device-round failures** — the supervisor's watchdog/rollback
  path: roll back to the last good checkpoint and replay the journal;
* **scalar degradation** — on some seeds one doc is force-demoted to scalar
  replay mid-run (the ladder's last rung) and must still hash byte-equal;
* **peer stall** — a bound-but-unresponsive TCP peer: the transport's
  socket deadline + bounded retry must surface a ``behind``
  :class:`SyncOutcome`, never a hang, and a real peer must then repair;
* **crash-restore** — the supervised session is dropped mid-run and rebuilt
  from its latest checkpoint, then repaired by overlapping redelivery.

The oracle is BYTE EQUALITY: after a final full anti-entropy repair the
chaos session's convergence digest must equal a fault-free session's digest
bit-for-bit, every doc's spans must equal the scalar oracle's, no doc may
remain decode-quarantined (auto re-admission), and nothing may remain
pending.  Any unhandled exception fails the campaign.
"""

from __future__ import annotations

import json
import random
import socket
import tempfile
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Dict, List

from ..api.batch import _oracle_doc
from ..core.errors import DeviceRoundError
from ..parallel.codec import encode_frame
from ..parallel.faults import FaultSpec, corrupt_detectably
from ..parallel.streaming import REASON_DECODE, REASON_DEVICE_ROUND
from ..parallel.supervisor import GuardedSession
from .fuzz import _campaign_session, generate_workload

#: the composed fault mix one chaos campaign applies to victim docs
CHAOS_SPEC = FaultSpec(
    drop_p=0.15, dup_p=0.15, reorder=True, truncate_p=0.3, bitflip_p=0.3
)


@dataclass
class ChaosReport:
    """Evidence from one seeded chaos campaign (all oracles already held —
    a violated oracle raises instead of returning)."""

    seed: int
    num_docs: int
    delivered_frames: int = 0
    corrupt_frames: int = 0
    dropped_frames: int = 0
    quarantined_peak: int = 0
    rollbacks: int = 0
    crash_restores: int = 0
    transport_behind: int = 0
    transport_repaired: bool = False
    isolation_checked: bool = False
    scalar_degraded_docs: int = 0
    final_digest: int = 0
    #: flight-recorder JSONL dumps the campaign's faults produced (the
    #: quarantine/rollback auto-dumps plus the campaign-end post-mortem)
    flight_dumps: int = 0

    def to_json(self) -> Dict:
        return asdict(self)


class _StallingPeer:
    """A TCP endpoint that accepts connections into its backlog and never
    speaks: the client's connect and first send succeed, then every recv
    stalls — exactly the peer failure `_recv_exact` used to hang on."""

    def __init__(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.address = self._sock.getsockname()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _chaos_transport_episode(workload, report: ChaosReport) -> None:
    """Peer-stall + repair: a stalled peer must yield a ``behind`` outcome
    within the retry budget (no hang, no exception), and a healthy peer must
    then converge the store."""
    from ..parallel.anti_entropy import ChangeStore
    from ..parallel.multihost import ReplicaServer, RetryPolicy, try_sync_with

    full = ChangeStore()
    for log in workload.values():
        for change in log:
            full.append(change)
    local = ChangeStore()
    policy = RetryPolicy(attempts=2, base_delay=0.01, max_delay=0.05,
                         jitter=0.5, timeout=0.3)

    stalled = _StallingPeer()
    try:
        outcome = try_sync_with(local, *stalled.address, retry=policy)
        assert outcome.behind and not outcome.ok, (
            "stalled peer must surface as a behind frontier"
        )
        report.transport_behind += 1
    finally:
        stalled.close()

    server = ReplicaServer(full, timeout=5.0)
    host, port = server.start()
    try:
        outcome = try_sync_with(local, host, port, retry=policy)
        assert outcome.ok and outcome.pulled > 0
    finally:
        server.stop()
    assert local.clock() == full.clock(), "repair round must converge the store"
    report.transport_repaired = True


def run_chaos(
    seed: int,
    num_docs: int = 6,
    ops_per_doc: int = 40,
    deadline: float = 60.0,
    transport: bool = True,
    crash: bool = True,
    checkpoint_every: int = 4,
) -> ChaosReport:
    """One seeded chaos campaign (see module docstring).  Raises on any
    oracle violation or unhandled fault; returns the evidence report."""
    rng = random.Random(seed ^ 0xC4A05)
    report = ChaosReport(seed=seed, num_docs=num_docs)

    workloads = generate_workload(seed, num_docs=num_docs, ops_per_doc=ops_per_doc)
    oracle_docs = [_oracle_doc(w) for w in workloads]

    # fault-free reference session: the byte-equality digest anchor
    clean = _campaign_session(num_docs, ops_per_doc)
    plans: List[List[bytes]] = []
    for d, w in enumerate(workloads):
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        chunk = rng.randrange(5, 12)
        frames = [
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ]
        plans.append(frames)
        for f in frames:
            clean.ingest_frame(d, f)
    clean.drain()
    clean_digest = clean.digest()

    # the supervised chaos session
    tmp = tempfile.TemporaryDirectory()
    try:
        from ..obs import FlightRecorder

        factory = lambda: _campaign_session(num_docs, ops_per_doc)  # noqa: E731
        # unthrottled flight recorder: every fault dumps, so the campaign's
        # post-mortem oracle below can demand the quarantine evidence even
        # across the crash-restore (which discards the in-memory ring)
        recorder = lambda: FlightRecorder(  # noqa: E731
            capacity=1024, dump_dir=Path(tmp.name) / "flight",
            min_dump_interval=0.0,
        )
        guarded = GuardedSession(
            factory, tmp.name, deadline=deadline,
            checkpoint_every=checkpoint_every, recorder=recorder(),
        )
        victims = set(rng.sample(range(num_docs),
                                 max(1, num_docs // 3)))

        # -- faulty delivery pass ------------------------------------------
        device_faults = rng.randrange(1, 3)
        for d, frames in enumerate(plans):
            delivery = []
            for f in frames:
                if rng.random() < CHAOS_SPEC.drop_p:
                    report.dropped_frames += 1
                    continue
                delivery.append(f)
                if rng.random() < CHAOS_SPEC.dup_p:
                    delivery.append(f)
            rng.shuffle(delivery)
            for f in delivery:
                if d in victims:
                    # detectable corruption only — the quarantine path's
                    # whole fault domain; see faults.corrupt_detectably for
                    # why undetectable damage models as clean delivery
                    bad = corrupt_detectably(f, rng, CHAOS_SPEC)
                    if bad is not None:
                        f = bad
                        report.corrupt_frames += 1
                guarded.ingest_frame(d, f)
                report.delivered_frames += 1
                if rng.random() < 0.3:
                    if device_faults and rng.random() < 0.15:
                        guarded.inject_failure(
                            DeviceRoundError("chaos: injected round failure")
                            if rng.random() < 0.5
                            else RuntimeError("chaos: injected XLA error")
                        )
                        device_faults -= 1
                    guarded.step()
        guarded.drain()
        report.quarantined_peak = max(
            report.quarantined_peak, len(guarded.quarantined())
        )

        # -- per-doc isolation oracle --------------------------------------
        # while >=1 doc sits in quarantine, every healthy doc that received
        # its full frame plan must already equal the oracle
        if report.quarantined_peak:
            quarantined_now = set(guarded.quarantined())
            for d in range(num_docs):
                if d in victims or d in quarantined_now:
                    continue
                # repair healthy docs' dropped frames first (clean redelivery)
                guarded.ingest_frames([(d, f) for f in plans[d]])
            guarded.drain()
            still_quarantined = set(guarded.quarantined())
            for d in range(num_docs):
                if d in victims or d in still_quarantined:
                    continue
                expected = oracle_docs[d].get_text_with_formatting(["text"])
                got = guarded.read(d)
                assert got == expected, (
                    f"seed={seed} doc={d}: healthy doc diverged while "
                    f"{sorted(still_quarantined)} were quarantined"
                )
            report.isolation_checked = bool(still_quarantined)

        # -- scalar-degradation rung (some seeds) --------------------------
        if rng.random() < 0.5:
            victim = rng.randrange(num_docs)
            guarded.session.force_fallback(
                victim, REASON_DEVICE_ROUND, "chaos: forced scalar replay"
            )
            report.scalar_degraded_docs = 1

        # -- peer stall + transport repair ---------------------------------
        if transport:
            _chaos_transport_episode(workloads[rng.randrange(num_docs)], report)

        # -- crash-restore -------------------------------------------------
        if crash:
            guarded.checkpoint()
            # deliver a bit more that the crash will lose
            for d, frames in enumerate(plans):
                if frames and rng.random() < 0.5:
                    guarded.ingest_frame(d, frames[rng.randrange(len(frames))])
            guarded.step()
            old_rollbacks = guarded.rollbacks
            del guarded  # crash: the process state is gone
            guarded = GuardedSession(
                factory, tmp.name, deadline=deadline,
                checkpoint_every=checkpoint_every, recorder=recorder(),
            )
            restored = guarded.manager.latest()
            assert restored is not None
            guarded.adopt_session(restored.session(drain=True))
            guarded.rollbacks = old_rollbacks
            report.crash_restores += 1

        # -- final anti-entropy repair + byte-equality oracle --------------
        for d, frames in enumerate(plans):
            guarded.ingest_frames([(d, f) for f in frames])
        guarded.drain()
        report.rollbacks = guarded.rollbacks

        assert guarded.session.pending_count() == 0, (
            f"seed={seed}: undelivered changes remain after repair"
        )
        decode_q = {
            d: r for d, r in guarded.quarantined().items()
            if r.reason == REASON_DECODE
        }
        assert not decode_q, (
            f"seed={seed}: docs {sorted(decode_q)} still decode-quarantined "
            "after clean redelivery (auto re-admission failed)"
        )
        final = guarded.digest()
        assert final == clean_digest, (
            f"seed={seed}: chaos digest {final:#010x} != fault-free digest "
            f"{clean_digest:#010x}"
        )
        report.final_digest = final
        for d in range(num_docs):
            expected = oracle_docs[d].get_text_with_formatting(["text"])
            got = guarded.read(d)
            assert got == expected, (
                f"seed={seed} doc={d}: spans diverge from oracle after repair"
            )

        # -- flight-recorder oracle ----------------------------------------
        # a campaign that quarantined anything must have produced at least
        # one automatic JSONL dump whose records parse and include the fault
        flight_dir = Path(tmp.name) / "flight"
        auto_dumps = sorted(flight_dir.glob("*.jsonl"))
        final_dump = guarded.recorder.dump(reason="campaign-end")
        records = []
        for dump in auto_dumps + [final_dump]:
            records.extend(
                json.loads(line)
                for line in dump.read_text().splitlines() if line
            )
        if report.corrupt_frames:
            assert auto_dumps, (
                f"seed={seed}: quarantine produced no flight-recorder dump"
            )
            assert any(
                r.get("kind") == "fault" and r.get("reason") == "quarantine"
                for r in records
            ), f"seed={seed}: flight dumps lack the quarantine fault record"
        # campaign-end post-mortem: the ring's spans must reconstruct the
        # recent rounds' stage timeline (guarded rounds + pipeline stages)
        span_names = {r["name"] for r in records if r.get("kind") == "span"}
        assert any(n.startswith("streaming.") for n in span_names) and (
            "supervisor.round" in span_names
        ), f"seed={seed}: flight dump spans missing the round stage timeline"
        report.flight_dumps = len(auto_dumps) + 1
        guarded.close()
    finally:
        tmp.cleanup()
    return report


def run_campaign(
    seeds: range, num_docs: int = 6, ops_per_doc: int = 40,
    verbose: bool = False, **kw,
) -> List[ChaosReport]:
    """Run one chaos campaign per seed; any oracle violation raises with the
    seed in its message.  Returns all evidence reports."""
    reports = []
    for seed in seeds:
        report = run_chaos(seed, num_docs=num_docs, ops_per_doc=ops_per_doc, **kw)
        reports.append(report)
        if verbose:
            print(
                f"seed {seed:4d}: frames={report.delivered_frames} "
                f"corrupt={report.corrupt_frames} "
                f"quarantine_peak={report.quarantined_peak} "
                f"rollbacks={report.rollbacks} "
                f"behind={report.transport_behind} "
                f"digest={report.final_digest:#010x}"
            )
    return reports
