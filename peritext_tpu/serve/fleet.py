"""Multi-host serving fleet: placement decisions executed as real state.

PR 6 built the deterministic :class:`~..parallel.router.FleetRouter` —
but its ``evacuate()``/``rebalance()`` moved *bookkeeping*, and nothing
detected or survived a dead serving host.  This module is the execution
layer that closes that gap: a :class:`FleetFrontend` places docs across
live :class:`~.mux.SessionMux` hosts via the router, ingests per-host
load back through the mux's own exporter surface
(``snapshot()["load"]`` — the same body ``/serve.json`` serves), detects
host death with the deterministic round-counted heartbeat leases of
:mod:`~..parallel.lease`, and executes placement changes as REAL doc-state
movement:

* **checkpoint ship** — a doc's durable form is its wire-frame history
  (event sourcing, the PR-1 checkpoint invariant); migration ships it to
  the target over the retrying multihost transport
  (:func:`~..parallel.multihost.ship_frames`) when the target serves a
  ship endpoint, in-process otherwise;
* **anti-entropy catch-up** — ops that landed on the source mid-move are
  shipped as frame-count-frontier diffs (duplicate-tolerant, the same
  merge semantics the CRDT already guarantees converge);
* **digest-checked cutover** — before the old slot is released, source and
  target must agree on the doc's full-state hash
  (:meth:`~..parallel.streaming.StreamingMerge.doc_digest`) BYTE-FOR-BYTE;
  a mismatch aborts the whole plan and rolls back atomically (router
  bookkeeping via ``rollback_moves``, serving map back to the sources,
  whose sessions were deliberately not released yet) — mirroring PR 6's
  atomic ``evacuate()`` plan semantics at the physical layer.

**Failover** (the lease's ``dead`` verdict): the dead host's docs re-place
from the last shipped checkpoint plus journal redelivery — the frontend
journals every ACKED (admitted) frame between checkpoint ships, so
``checkpoint ∪ journal ⊇ acked ops`` is an invariant and every acked op
survives the host that held it.  While a doc is mid-failover (or
mid-cutover) its submissions get typed ``delay`` verdicts; a doc failover
could not re-place (no fleet capacity) sheds ``failover`` — zero silent
drops extends fleet-wide, and the accounting identity
``submitted == admitted + delayed + shed`` holds over every verdict the
frontend returned.  The flight recorder dumps the failover timeline.

Wall-clock reads are legal here (``serve/`` sits outside graftlint's
merge scope); everything that must be deterministic — lease verdicts,
placement — lives in ``parallel/`` where PTL006 guards it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.errors import PeritextError
from ..obs import GLOBAL_COUNTERS, GLOBAL_TRACER
from ..parallel.lease import DEAD, HeartbeatLedger
from ..parallel.router import FleetRouter, PlacementError
from .auth import AuthError
from .admission import (
    ADMIT,
    AdmissionController,
    DELAY,
    SHED,
    SHED_CAPACITY,
    SHED_FAILOVER,
    SHED_UNAUTHORIZED,
    SHED_UNKNOWN_SESSION,
    Verdict,
)
from .mux import SessionMux


class HostDown(PeritextError):
    """The addressed serving host is dead (raised inside the fleet layer,
    converted to typed verdicts at the frontend edge — a client never sees
    this exception)."""


class CutoverError(PeritextError):
    """Migration cutover digest mismatch: source and target disagree on
    the doc's full-state hash, so the old slot must NOT be released — the
    plan rolls back atomically."""


class FleetHost:
    """One serving host in the fleet: a :class:`SessionMux`, its doc-key →
    session mapping, and (optionally) a real TCP ship endpoint
    (``transport=True`` starts a :class:`~..parallel.multihost.ReplicaServer`
    whose ``on_ship`` lands checkpoint frames in this mux's doc slots).

    Thread-safe around the mux/session: ship receives run on transport
    handler threads while the frontend pumps on its own."""

    def __init__(self, name: str, mux: SessionMux, transport: bool = False,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.name = name
        self.mux = mux
        self.alive = True
        self._lock = threading.RLock()
        self._docs: Dict[str, int] = {}
        self.server = None
        if transport:
            from ..parallel.anti_entropy import ChangeStore
            from ..parallel.multihost import ReplicaServer

            self.server = ReplicaServer(
                ChangeStore(), host=host, port=port, on_ship=self._on_ship,
            )
            self.server.start()

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return self.server.address if self.server is not None else None

    # -- liveness -------------------------------------------------------------

    def heartbeat(self) -> bool:
        """The lease ledger's beat input.  In-process liveness here; a
        WAN deployment would probe the wire — the DETERMINISM lives in the
        ledger, the beat source is deliberately pluggable."""
        return self.alive

    def kill(self) -> None:
        """Chaos: the host dies mid-traffic — the mux stops answering, the
        ship endpoint closes, heartbeats stop.  Doc state on this host is
        GONE as far as the fleet is concerned (failover restores from
        checkpoint + journal)."""
        self.alive = False
        if self.server is not None:
            self.server.stop()

    def _require_alive(self) -> None:
        if not self.alive:
            raise HostDown(self.name)

    # -- doc slots ------------------------------------------------------------

    def session_of(self, doc_key: str) -> Optional[int]:
        with self._lock:
            return self._docs.get(doc_key)

    def ensure_doc(self, doc_key: str,
                   client: str = "fleet") -> Tuple[Optional[int], Verdict]:
        """Claim (or find) this host's mux session for ``doc_key``."""
        with self._lock:
            self._require_alive()
            sid = self._docs.get(doc_key)
            if sid is not None:
                return sid, Verdict(kind=ADMIT)
            sid, verdict = self.mux.open_session(client)
            if sid is not None:
                self._docs[doc_key] = sid
            return sid, verdict

    def doc_have(self, doc_key: str) -> int:
        """How many frames this host already holds for ``doc_key`` (0 when
        it has no slot).  The resume input for a retried migration: mux
        slots are append-only, so a failed move KEEPS its doc→slot
        reservation and the next attempt ships into the same slot instead
        of burning a fresh one per retry."""
        with self._lock:
            sid = self._docs.get(doc_key)
            if sid is None or not self.alive:
                return 0
            doc = self.mux.sessions()[sid].doc_index
            return len(self.mux.session.doc_history_frames(doc))

    def doc_append_only(self, doc_key: str) -> bool:
        """Whether the doc's frame history is append-only (frame-mode
        docs): True means a partial ship resumes as a prefix append;
        False (fallback/object docs re-encode the whole log) means a
        resumed ship must re-send in full — the receiver's merge is
        idempotent either way."""
        with self._lock:
            self._require_alive()
            doc = self.mux.sessions()[self._docs[doc_key]].doc_index
            return bool(self.mux.session.docs[doc].frame_mode)

    def release_doc(self, doc_key: str) -> None:
        """Release the doc's serving slot (migration cutover committed, or
        the slot's state is distrusted after a cutover digest mismatch):
        the session closes; its resident device state becomes garbage the
        append-only slot map simply stops reaching (mux slots are
        append-only by design — see SessionMux)."""
        with self._lock:
            sid = self._docs.pop(doc_key, None)
            if sid is not None:
                self.mux.close_session(sid)

    # -- the serving surface --------------------------------------------------

    def submit(self, doc_key: str, frame: bytes) -> Verdict:
        with self._lock:
            self._require_alive()
            return self.mux.submit(self._docs[doc_key], frame)

    def pump(self) -> int:
        with self._lock:
            if not self.alive:
                return 0
            return self.mux.pump()

    def flush(self) -> int:
        with self._lock:
            self._require_alive()
            return self.mux.flush()

    # -- migration state access ----------------------------------------------

    def doc_frames(self, doc_key: str) -> List[bytes]:
        """The doc's ingested frame history (flushing the open round first
        so every ACKED frame is in it) — the checkpoint-ship payload."""
        with self._lock:
            self._require_alive()
            self.mux.flush()
            return self.mux.session.doc_history_frames(self._docs[doc_key])

    def doc_digest(self, doc_key: str) -> int:
        """The doc's full-state hash (flushed first) — the cutover oracle."""
        with self._lock:
            self._require_alive()
            self.mux.flush()
            return self.mux.session.doc_digest(self._docs[doc_key])

    def ingest_doc_frames(self, doc_key: str, frames: List[bytes],
                          base: int = 0) -> int:
        """The ship receiver: land checkpoint/catch-up frames in the doc's
        slot and drain.  ``base`` is the sender's belief of how many frames
        this host already holds; frames this host provably has (history
        longer than ``base``) are skipped so a retried ship stays a prefix
        append.  Returns the post-merge history length (the ack's
        ``have``).  Raises :class:`PlacementError` when the mux is out of
        slots — a ship to a full host must fail loudly, never truncate."""
        with self._lock:
            self._require_alive()
            sid, _ = self.ensure_doc(doc_key, client="migration")
            if sid is None:
                raise PlacementError(
                    f"host {self.name!r}: no slot for shipped doc {doc_key!r}"
                )
            sess = self.mux.session
            doc = self.mux.sessions()[sid].doc_index
            have = len(sess.doc_history_frames(doc))
            skip = max(0, have - int(base))
            for frame in frames[skip:]:
                sess.ingest_frame(doc, frame, on_corrupt="quarantine")
            while sess.drain() > 0:
                pass
            return len(sess.doc_history_frames(doc))

    def _on_ship(self, doc_key: str, frames: List[bytes], base: int) -> int:
        return self.ingest_doc_frames(doc_key, frames, base)

    def stop(self) -> None:
        if self.server is not None:
            self.server.stop()

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "alive": self.alive,
                "docs": sorted(self._docs),
                "address": list(self.address) if self.address else None,
                "serve": self.mux.snapshot() if self.alive else None,
            }


@dataclass
class FleetStats:
    """Fleet-wide verdict accounting over every submission the frontend
    answered (host-mux verdicts routed through plus the frontend's own
    out-of-band failover/auth/capacity verdicts).  The zero-silent-drops
    identity ``submitted == admitted + delayed + shed`` is the chaos
    harness's fleet-wide oracle."""

    submitted: int = 0
    admitted: int = 0
    delayed: int = 0
    shed: int = 0
    shed_reasons: Dict[str, int] = field(default_factory=dict)

    def observe(self, verdict: Verdict) -> Verdict:
        self.submitted += 1
        if verdict.kind == ADMIT:
            self.admitted += 1
        elif verdict.kind == DELAY:
            self.delayed += 1
        elif verdict.kind == SHED:
            self.shed += 1
            self.shed_reasons[verdict.reason] = (
                self.shed_reasons.get(verdict.reason, 0) + 1
            )
        return verdict

    def accounted(self) -> bool:
        return self.submitted == self.admitted + self.delayed + self.shed

    def to_json(self) -> Dict:
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "delayed": self.delayed,
            "shed": self.shed,
            "shed_reasons": dict(sorted(self.shed_reasons.items())),
        }


class FleetFrontend:
    """Places docs across live serving hosts and keeps them alive (see
    module doc).  ``checkpoint_every`` is in frontend bookkeeping ROUNDS
    (the same unit the heartbeat lease counts); ``auth`` is an optional
    :class:`~.auth.SessionKeyring` verified at the fleet edge — on EVERY
    submit, not just at open: unlike a mux session id (a server-assigned
    opaque bearer), the fleet edge routes by ``doc_key``, a public
    client-chosen name, so possession of the name must never stand in for
    the credential.  ``recorder`` an optional
    :class:`~..obs.FlightRecorder` that dumps the failover timeline."""

    def __init__(
        self,
        router: Optional[FleetRouter] = None,
        lease_rounds: int = 3,
        checkpoint_every: int = 4,
        auth=None,
        recorder=None,
        retry=None,
        tracer=None,
    ) -> None:
        from ..parallel.multihost import RetryPolicy

        self.router = router if router is not None else FleetRouter()
        self.ledger = HeartbeatLedger(lease_rounds)
        self.hosts: Dict[str, FleetHost] = {}
        self.auth = auth
        self.recorder = recorder
        self.retry = retry if retry is not None else RetryPolicy(
            attempts=3, base_delay=0.01, max_delay=0.2, timeout=5.0,
        )
        self.tracer = tracer if tracer is not None else GLOBAL_TRACER
        self.checkpoint_every = int(checkpoint_every)
        #: out-of-band typed verdicts (failover delay/shed, auth, capacity):
        #: the queue logic is unused, the verdict accounting + counters are
        #: the point (serve.shed.<reason> telemetry stays one vocabulary)
        self._oob = AdmissionController()
        self.stats = FleetStats()
        #: doc_key -> host currently SERVING it.  The router tracks
        #: PLACEMENT (flips at plan time); this map flips only at cutover —
        #: mid-move ops keep landing on the source, which is what the
        #: catch-up leg ships
        self._serving: Dict[str, str] = {}
        self._clients: Dict[str, str] = {}
        #: per-doc acked-frame journal since the last checkpoint ship
        self._journal: Dict[str, List[bytes]] = {}
        #: per-doc set of every frame ever journaled (shares the journal's
        #: bytes objects): a client retrying its whole plan after a
        #: failover re-admits byte-identical frames, and without dedup
        #: each retry pass would permanently multiply the standby store
        self._acked_frames: Dict[str, set] = {}
        #: per-doc last shipped checkpoint (the frontend is the fleet's
        #: standby store: ``checkpoint ∪ journal ⊇ acked``)
        self._checkpoint: Dict[str, List[bytes]] = {}
        #: docs failover could not re-place (shed(failover) until capacity)
        self._failed_docs: set = set()
        #: docs paused for cutover (typed delay verdicts)
        self._moving: set = set()
        self.rounds = 0
        self.failovers = 0
        self.failover_docs = 0
        self.migrations = 0
        self.migration_rollbacks = 0
        self.checkpoint_ships = 0

    # -- fleet membership -----------------------------------------------------

    def add_host(self, name: str, mux: SessionMux,
                 capacity: Optional[int] = None,
                 transport: bool = False) -> FleetHost:
        """Register a serving host.  Re-registering a name whose lease is
        DEAD is the re-admission path (the only way out of the lease
        latch): the zombie's remnants are torn down and the name starts a
        fresh lease.  A live name re-registering is an operator error and
        raises BEFORE any state mutates — no half-registered fleet."""
        if getattr(mux, "auth", None) is not None:
            # the fleet edge is the tenant auth boundary; host muxes sit
            # behind it and are driven by internal clients (migration
            # ships, failover redelivery) that hold no tokens — a mux-level
            # keyring would make every failover/migration shed unauthorized
            raise AuthError(
                f"host {name!r}: fleet-managed muxes must not enable "
                "mux-level auth; pass the keyring to FleetFrontend(auth=)"
            )
        if name in self.hosts:
            if self.hosts[name].alive and self.ledger.verdict(name) != DEAD:
                raise ValueError(f"host {name!r} already registered")
            # dead-host re-admission: fail_host() already unassigned its
            # placements, so the draining router slot removes cleanly
            self.hosts[name].stop()
            self.router.remove_host(name)
            self.ledger.reset(name)
        self.router.add_host(
            name,
            capacity if capacity is not None else mux.session.num_docs,
        )
        host = FleetHost(name, mux, transport=transport)
        self.hosts[name] = host
        self.ledger.track(name)
        return host

    def stop(self) -> None:
        for host in self.hosts.values():
            host.stop()

    # -- the client surface ---------------------------------------------------

    def open_doc(self, doc_key: str, client: str,
                 token: Optional[str] = None) -> Verdict:
        """Place + open one doc on the fleet.  Typed verdicts only:
        ``unauthorized`` (auth edge, checked first; on an auth-enabled
        fleet re-opening a served doc also requires the REGISTERED owner —
        doc keys are public names, so any-valid-token would hand one
        tenant's doc to another), ``capacity`` (no host can take it), else
        ``admit``."""
        if self.auth is not None and not self.auth.verify(client, token):
            return self.stats.observe(
                self._oob.shed_out_of_band(SHED_UNAUTHORIZED))
        if doc_key in self._serving:
            if self.auth is not None and client != self._clients.get(doc_key):
                return self.stats.observe(
                    self._oob.shed_out_of_band(SHED_UNAUTHORIZED))
            return self.stats.observe(Verdict(kind=ADMIT))
        try:
            placed = self.router.place(doc_key, size=1)
        except PlacementError:
            return self.stats.observe(
                self._oob.shed_out_of_band(SHED_CAPACITY))
        sid, verdict = self.hosts[placed].ensure_doc(doc_key, client)
        if sid is None:
            self.router.release(doc_key)
            return self.stats.observe(verdict)
        self._serving[doc_key] = placed
        self._clients[doc_key] = client
        self._journal.setdefault(doc_key, [])
        GLOBAL_COUNTERS.add("fleet.docs_opened")
        return self.stats.observe(verdict)

    def submit(self, doc_key: str, frame: bytes,
               token: Optional[str] = None) -> Verdict:
        """Route one frame to the doc's serving host.  Every outcome is a
        typed verdict; ``admit`` additionally journals the frame (the
        acked-op survival invariant).  An auth-enabled fleet verifies the
        token on EVERY submit against the doc's registered owner —
        ``doc_key`` is a public name, not a bearer, so the check cannot be
        an opt-in (and an unknown doc sheds ``unauthorized`` before
        ``unknown-session``, leaking no doc existence to probes)."""
        if (self.auth is not None
                and not self.auth.verify(self._clients.get(doc_key, ""),
                                         token)):
            return self.stats.observe(
                self._oob.shed_out_of_band(SHED_UNAUTHORIZED))
        if doc_key in self._failed_docs:
            return self.stats.observe(
                self._oob.shed_out_of_band(SHED_FAILOVER))
        if doc_key in self._moving:
            return self.stats.observe(self._oob.delay_out_of_band(0.01))
        serving = self._serving.get(doc_key)
        if serving is None:
            return self.stats.observe(
                self._oob.shed_out_of_band(SHED_UNKNOWN_SESSION))
        host = self.hosts[serving]
        try:
            verdict = host.submit(doc_key, frame)
        except HostDown:
            # the host died and the lease has not expired yet (or failover
            # is about to run): the client retries — nothing was taken
            return self.stats.observe(self._oob.delay_out_of_band(0.05))
        if verdict.kind == ADMIT:
            # dedup against everything ever journaled: a post-failover
            # client retrying its whole plan re-admits byte-identical
            # frames, and the standby store must not grow per retry pass
            seen = self._acked_frames.setdefault(doc_key, set())
            if frame not in seen:
                seen.add(frame)
                self._journal.setdefault(doc_key, []).append(frame)
        return self.stats.observe(verdict)

    def patches(self, doc_key: str):
        host = self.hosts[self._serving[doc_key]]
        return host.mux.patches(host.session_of(doc_key))

    def doc_digest(self, doc_key: str) -> int:
        return self.hosts[self._serving[doc_key]].doc_digest(doc_key)

    # -- the frontend bookkeeping round ---------------------------------------

    def round(self) -> Dict[str, str]:
        """One observation round: heartbeats → lease ledger (newly-dead
        leases trigger failover), pump every live host's round window,
        re-ingest per-host load through the mux exporter surface, and ship
        checkpoints every ``checkpoint_every`` rounds.  Returns the lease
        verdicts."""
        self.rounds += 1
        beats = {name: host.heartbeat()
                 for name, host in sorted(self.hosts.items())}
        verdicts = self.ledger.tick(beats)
        for name in self.ledger.newly_dead():
            self._failover(name)
        for name in sorted(self.hosts):
            self.hosts[name].pump()
        self.observe_loads()
        if self.checkpoint_every and self.rounds % self.checkpoint_every == 0:
            self.checkpoint_ship()
        return verdicts

    def observe_loads(self) -> None:
        """Fold every live host's measured load (mux ``snapshot()["load"]``
        — the ``/serve.json`` surface) into the router's placement state."""
        for name in sorted(self.hosts):
            host = self.hosts[name]
            if not host.alive:
                continue
            load = host.mux.load_report()
            self.router.observe(
                name,
                slot_load=load["slot_load"],
                host_bound_load=load["host_bound_load"],
                page_load=load.get("page_load"),
            )

    def observe_lag(self, name: str, lag_ops: int) -> None:
        """Fold a host's replication-lag watermark (a ConvergenceMonitor
        ``ops_behind`` reading) into placement."""
        self.router.observe(name, lag_ops=lag_ops)

    def checkpoint_ship(self) -> int:
        """Fold every doc's journal into the frontend's standby checkpoint
        and restart the journal empty: after this, the checkpoint alone
        covers every acked op so far.  The fold is O(journal), never
        O(history) — every acked frame already flowed through
        :meth:`submit`'s journal (the frontend IS the fleet's write path;
        ``open_doc`` creates the doc), so pulling the host's full frame
        history every few rounds would re-copy the same bytes forever for
        nothing.  A dead host cannot stall this: no host is touched.
        Returns how many docs folded journal frames."""
        shipped = 0
        for doc_key in sorted(self._serving):
            journal = self._journal.get(doc_key)
            if not journal:
                continue
            self._checkpoint.setdefault(doc_key, []).extend(journal)
            self._journal[doc_key] = []
            shipped += 1
        self.checkpoint_ships += 1
        GLOBAL_COUNTERS.add("fleet.checkpoint_ships")
        return shipped

    # -- failover --------------------------------------------------------------

    def _failover(self, dead: str) -> None:
        """The lease latched dead: forget the host's placements and re-home
        every doc from durable state — last shipped checkpoint + journal
        redelivery (frames are duplicate-tolerant, so overlap between the
        two is harmless and every ACKED op is in their union)."""
        self.failovers += 1
        GLOBAL_COUNTERS.add("fleet.failovers")
        if self.recorder is not None:
            self.recorder.fault(
                "host-death", host=dead, round=self.rounds,
                docs=len(self.hosts[dead].snapshot()["docs"])
                if dead in self.hosts else 0,
            )
        with self.tracer.span("fleet.failover", host=dead) as sp:
            lost = self.router.fail_host(dead)
            replaced, failed = [], []
            for doc_key, size, bound in lost:
                if self._re_place(doc_key, size, bound):
                    replaced.append(doc_key)
                else:
                    failed.append(doc_key)
            sp.args.update(replaced=len(replaced), failed=len(failed))
        if self.recorder is not None:
            self.recorder.fault(
                "failover-complete", host=dead, round=self.rounds,
                replaced=sorted(replaced), failed=sorted(failed),
            )

    def _re_place(self, doc_key: str, size: int, bound: bool) -> bool:
        try:
            target_name = self.router.place(doc_key, size, bound)
        except PlacementError:
            self._failed_docs.add(doc_key)
            GLOBAL_COUNTERS.add("fleet.failover_unplaced_docs")
            return False
        target = self.hosts[target_name]
        frames = (self._checkpoint.get(doc_key, [])
                  + self._journal.get(doc_key, []))
        try:
            sid, _ = target.ensure_doc(
                doc_key, self._clients.get(doc_key, "fleet"))
            if sid is None:
                raise PlacementError(f"no slot on {target_name!r}")
            # redelivery rides the same ship leg migrations use (TCP when
            # the target serves a ship endpoint)
            self._ship(target, doc_key, frames, base=0)
        except (HostDown, PlacementError, OSError):
            self.router.release(doc_key)
            # the target's doc→slot reservation (if the ship got that far)
            # is deliberately KEPT: retry_failed() re-ships into the same
            # slot — frames are duplicate-tolerant and redelivery always
            # sends checkpoint+journal with base=0, so the receiver's
            # prefix-skip resumes exactly where the dead attempt stopped
            self._failed_docs.add(doc_key)
            GLOBAL_COUNTERS.add("fleet.failover_unplaced_docs")
            return False
        self._serving[doc_key] = target_name
        self._failed_docs.discard(doc_key)
        self.failover_docs += 1
        GLOBAL_COUNTERS.add("fleet.failover_docs")
        return True

    def retry_failed(self) -> int:
        """Re-attempt failover placement for docs that shed ``failover``
        (capacity may have returned: a new host registered, or load
        drained).  Returns how many re-homed."""
        healed = 0
        for doc_key in sorted(self._failed_docs):
            if self._re_place(doc_key, 1, False):
                healed += 1
        return healed

    # -- migration (the evacuate/rebalance executor) ---------------------------

    def _ship(self, target: FleetHost, doc_key: str,
              frames: List[bytes], base: int) -> int:
        """One ship leg: over the retrying multihost transport when the
        target serves a ship endpoint, in-process otherwise (identical
        receiver semantics — ``FleetHost.ingest_doc_frames`` either way)."""
        if target.address is not None:
            from ..parallel.multihost import ship_frames

            return ship_frames(
                *target.address, doc_key, frames, base=base,
                retry=self.retry, tracer=self.tracer,
            )
        return target.ingest_doc_frames(doc_key, frames, base=base)

    def _ship_delta(self, target: FleetHost, doc_key: str,
                    prev: List[bytes], current: List[bytes],
                    have: int) -> Tuple[int, bool]:
        """One catch-up leg: ship whatever ``current`` holds beyond
        ``prev`` (the last-shipped history).  Frame-mode docs are
        append-only, so the tail ships; fallback/object docs RE-ENCODE
        their whole log as one frame whose content changes but whose
        count does not — those re-ship in full with ``base=have`` so the
        receiver's prefix-skip cannot drop the re-encoded payload
        (its merge is idempotent, overlap is harmless).  Returns
        ``(new have, whether anything shipped)``."""
        if current == prev:
            return have, False
        if current[:len(prev)] == prev:
            return (self._ship(target, doc_key, current[len(prev):],
                               base=have), True)
        return self._ship(target, doc_key, current, base=have), True

    def _execute_move(self, doc_key: str, to_name: str,
                      catch_up_rounds: int = 3) -> Tuple[str, int]:
        """Physically move one doc to ``to_name``: checkpoint ship →
        unpaused anti-entropy catch-up (ops landing mid-move keep hitting
        the source and ship as frame-frontier diffs) → cutover pause
        (typed delay verdicts) → final catch-up → byte-equality digest
        check → serving-map flip.  The SOURCE slot is NOT released here —
        the plan executor releases sources only once the whole plan
        committed, so a later cutover failure can still roll everything
        back onto intact source state.  Returns ``(source host name,
        frames shipped)``; raises :class:`CutoverError` on digest
        mismatch (doc unpaused, still serving on the source)."""
        src_name = self._serving[doc_key]
        src, target = self.hosts[src_name], self.hosts[to_name]
        with self.tracer.span("fleet.migrate", doc=doc_key,
                              src=src_name, dst=to_name):
            frames = src.doc_frames(doc_key)
            have0 = target.doc_have(doc_key)
            if have0 == 0:
                have = self._ship(target, doc_key, frames, base=0)
            elif src.doc_append_only(doc_key):
                # resumed slot (a prior attempt failed mid-ship): the
                # target's partial history is a prefix of this same
                # append-only list — ship only the missing tail
                have = (self._ship(target, doc_key, frames[have0:],
                                   base=have0)
                        if len(frames) > have0 else have0)
            else:
                # resumed slot, re-encoded history: the receiver's partial
                # content is unknowable by count, so re-ship in full with
                # base=have0 (no prefix-skip; the merge is idempotent)
                have = self._ship(target, doc_key, frames, base=have0)
            prev, total = frames, len(frames)
            # catch-up: ops that landed while the checkpoint shipped
            for _ in range(max(0, catch_up_rounds)):
                current = src.doc_frames(doc_key)
                have, changed = self._ship_delta(
                    target, doc_key, prev, current, have)
                prev, total = current, len(current)
                if not changed:
                    break
            self._moving.add(doc_key)
            try:
                current = src.doc_frames(doc_key)
                have, _ = self._ship_delta(
                    target, doc_key, prev, current, have)
                total = len(current)
                src_digest = src.doc_digest(doc_key)
                dst_digest = target.doc_digest(doc_key)
                if src_digest != dst_digest:
                    GLOBAL_COUNTERS.add("fleet.cutover_mismatches")
                    # the target slot's state failed byte equality: it is
                    # DISTRUSTED and must never be resumed into — drop the
                    # reservation (the rare case where a slot is burned;
                    # transport failures keep theirs for resume)
                    target.release_doc(doc_key)
                    raise CutoverError(
                        f"doc {doc_key!r} {src_name}->{to_name}: cutover "
                        f"digest {dst_digest:#010x} != source "
                        f"{src_digest:#010x}"
                    )
                # cutover: new ops route to the target from here on
                self._serving[doc_key] = to_name
            finally:
                self._moving.discard(doc_key)
        self.migrations += 1
        GLOBAL_COUNTERS.add("fleet.migrations")
        return src_name, total

    def _execute_plan(self,
                      plan: List[Tuple[str, str, str]]) -> List[Tuple[str, str, str]]:
        """Execute a router move plan atomically: every cutover must pass
        its digest check or NONE of the plan lands — executed cutovers
        revert to their (still intact) sources, target doc→slot
        reservations are kept so a retried plan resumes its ships (a
        digest-mismatched slot alone is distrusted and dropped), and the
        router's bookkeeping rolls back to the pre-plan placement.
        Source slots release only after the whole plan committed."""
        executed: List[Tuple[str, str, str]] = []
        try:
            for doc_key, from_name, to_name in plan:
                self._execute_move(doc_key, to_name)
                executed.append((doc_key, from_name, to_name))
        except (CutoverError, HostDown, PlacementError, PeritextError,
                ValueError, OSError):
            for doc_key, from_name, _ in reversed(executed):
                self._serving[doc_key] = from_name
            # target doc→slot reservations are KEPT on rollback: mux slots
            # are append-only, so releasing could never reclaim capacity —
            # a retried plan resumes each ship into the same slot instead
            # of burning a fresh one per attempt (the shipped state is a
            # valid partial merge; only a cutover digest MISMATCH distrusts
            # a slot, and _execute_move releases that one itself)
            self.router.rollback_moves(plan)
            self.migration_rollbacks += 1
            GLOBAL_COUNTERS.add("fleet.migration_rollbacks")
            raise
        for doc_key, from_name, _ in executed:
            self.hosts[from_name].release_doc(doc_key)
        return executed

    def migrate(self, doc_key: str, to_name: str) -> None:
        """Directed single-doc migration (router bookkeeping + physical
        move, atomic)."""
        from_name = self._serving[doc_key]
        self.router.move(doc_key, to_name)
        self._execute_plan([(doc_key, from_name, to_name)])

    def evacuate(self, name: str) -> List[Tuple[str, str, str]]:
        """Drain one host FOR REAL: the router's atomic plan, executed as
        checkpoint ship + catch-up + digest-checked cutover per doc.  All
        or nothing (see :meth:`_execute_plan`)."""
        plan = self.router.evacuate(name)
        return self._execute_plan(plan)

    def rebalance(self, max_moves: int = 8) -> List[Tuple[str, str, str]]:
        """The router's bounded-greedy rebalance, executed as real state
        movement.  All or nothing."""
        plan = self.router.rebalance(max_moves=max_moves)
        return self._execute_plan(plan)

    # -- readout ---------------------------------------------------------------

    def flush(self) -> None:
        for name in sorted(self.hosts):
            host = self.hosts[name]
            if host.alive:
                host.flush()

    def snapshot(self) -> Dict:
        """The ``/fleet.json`` body (golden-shape pinned): lease table,
        router placement, per-host serve summaries, durable-state
        bookkeeping and the fleet-wide verdict accounting."""
        return {
            "rounds": self.rounds,
            "hosts": {
                name: self.hosts[name].snapshot()
                for name in sorted(self.hosts)
            },
            "leases": self.ledger.snapshot(),
            "router": self.router.snapshot(),
            "serving": dict(sorted(self._serving.items())),
            "moving": sorted(self._moving),
            "failed_docs": sorted(self._failed_docs),
            "failovers": self.failovers,
            "failover_docs": self.failover_docs,
            "migrations": self.migrations,
            "migration_rollbacks": self.migration_rollbacks,
            "checkpoint_ships": self.checkpoint_ships,
            "journal_frames": sum(len(v) for v in self._journal.values()),
            "checkpoint_docs": len(self._checkpoint),
            "verdicts": self.stats.to_json(),
            "auth": (self.auth.snapshot()
                     if self.auth is not None else None),
        }
