"""peritext_tpu.serve — the multi-tenant serving tier.

The layer the ROADMAP's "production serving tier" item names: everything
below this package converges documents (``parallel/streaming``), heals
replicas (``parallel/gossip``) and measures itself (``obs/``), but nothing
accepted *client sessions*, shed load when ingest outran device rounds, or
traded latency for batch occupancy on purpose.  Three cooperating pieces:

* :mod:`.admission` — typed admission verdicts (``admit`` / ``delay(hint)``
  / ``shed(reason)``) over a bounded ingest queue with watermark-driven
  backpressure.  A client op is NEVER silently dropped: every submission
  either enters the queue or comes back with a typed verdict, and the
  accounting identity ``submitted == admitted + delayed + shed`` is an
  invariant the chaos harness asserts under 2x overload.
* :mod:`.mux` — :class:`SessionMux`: many client sessions multiplexed onto
  one :class:`~..parallel.streaming.StreamingMerge`'s slot buckets, behind
  the existing ``InputOperation``/``Patch`` boundary (clients submit wire
  frames or ``Change`` lists; they read per-session ``Patch`` streams).
  The round-open window is autotuned from the rolling round-latency
  histogram (:class:`BatchWindowTuner` — the batching-window sibling of
  the PR-3 supervisor deadline autotuner), and sustained per-session
  overload degrades through the PR-1 quarantine/fallback ladder
  (``force_fallback``: scalar replay, degraded but correct) instead of
  shedding one hot doc's writes forever.
* :mod:`.fused` — :class:`FusedMuxGroup`: many tenants' muxes fused onto
  shared ``static_rounds`` device lanes (doc-row ranges assigned by the
  plan tier's :class:`~..plan.fusion.FusionGroup`), so one batching
  window commits ONE staged device program per touched lane instead of
  one per tenant — per-tenant admission, verdicts, and patch streams are
  untouched, and byte equality with the unfused path holds per tenant.
* :mod:`.traffic` — the sustained OPEN-LOOP traffic generator behind
  ``bench.py --mode serve``: arrival times are fixed by the offered rate,
  never by service completions, so queue growth under saturation is
  visible instead of self-throttled; the ladder sweeps the rate until the
  p99 apply-latency SLO breaks and reports docs/s at the SLO.  Also the
  reconnect-storm workload (ROADMAP scenario item).

Doc *placement* across a serving fleet is deliberately NOT here: the
:class:`~..parallel.router.FleetRouter` lives in merge scope
(``parallel/``) because placement must be a deterministic function of the
observed load/lag state — graftlint's PTL006 guards it against wall-clock
or RNG reads, while this package (wall-clock timing, queues, sleeps) sits
outside merge scope by design.
"""

from .admission import (
    ADMIT,
    AdmissionController,
    DELAY,
    SHED,
    SHED_CAPACITY,
    SHED_DEGRADED,
    SHED_FAILOVER,
    SHED_OVERLOAD,
    SHED_QUEUE_FULL,
    SHED_REASONS,
    SHED_SESSION_QUOTA,
    SHED_UNAUTHORIZED,
    SHED_UNKNOWN_SESSION,
    Verdict,
)
from .auth import AuthError, SessionKeyring
from .fleet import CutoverError, FleetFrontend, FleetHost, FleetStats, HostDown
from .fused import FusedMuxGroup, default_lane_factory
from .mux import BatchWindowTuner, SessionMux
from .traffic import (
    LadderRung,
    OpenLoopResult,
    build_arrivals,
    run_open_loop,
    sustained_ladder,
)

__all__ = [
    "ADMIT",
    "AdmissionController",
    "AuthError",
    "BatchWindowTuner",
    "CutoverError",
    "DELAY",
    "FleetFrontend",
    "FleetHost",
    "FleetStats",
    "FusedMuxGroup",
    "HostDown",
    "LadderRung",
    "OpenLoopResult",
    "SHED",
    "SHED_CAPACITY",
    "SHED_DEGRADED",
    "SHED_FAILOVER",
    "SHED_OVERLOAD",
    "SHED_QUEUE_FULL",
    "SHED_REASONS",
    "SHED_SESSION_QUOTA",
    "SHED_UNAUTHORIZED",
    "SHED_UNKNOWN_SESSION",
    "SessionKeyring",
    "SessionMux",
    "Verdict",
    "build_arrivals",
    "default_lane_factory",
    "run_open_loop",
    "sustained_ladder",
]
