"""Per-session wire auth: HMAC-signed session tokens, verified at admission.

A multi-tenant serving tier that shares one device pipeline across clients
needs an identity check BEFORE any shared resource is touched: a bad or
missing credential must cost the attacker one admission decision, not a
queue slot, a doc slot, or a device round.  The scheme here is the
smallest honest one:

* a :class:`SessionKeyring` holds named HMAC-SHA256 keys; exactly one is
  the **minting** key, any number are **accepted** for verification;
* a token is ``kid.hex(hmac(key_kid, client))`` — it binds the CLIENT
  identity (the string a session is opened under), so a token leaked from
  one tenant cannot open sessions as another;
* the mux verifies at ``open_session`` (session admission) and — when
  ``auth_per_frame`` — at every ``submit``; failure is the typed
  ``shed(reason="unauthorized")`` verdict, counted in
  ``peritext_serve_shed_reason_total`` like every other shed.  Zero silent
  drops extends to auth failures.

**Key rotation without dropping live sessions** (the ROADMAP requirement):
:meth:`SessionKeyring.rotate` installs a new minting key while keeping the
old key in the accepted set — tokens minted before the rotation keep
verifying, so live sessions (and per-frame-auth clients that cached their
token) ride through the rotation untouched.  :meth:`retire` removes a key
from the accepted set once its tokens are known-drained; only THEN do its
tokens start shedding ``unauthorized``.

Timing discipline: verification uses ``hmac.compare_digest`` (constant
time in the token length), and an unknown ``kid`` takes the same comparison
path against a dummy key so key-name probing learns nothing.
"""

from __future__ import annotations

import hmac
import hashlib
from typing import Dict, List, Optional

_DIGEST = hashlib.sha256
#: compared against when the token names an unknown key: the code path
#: (one HMAC + one compare_digest) is identical to the known-key path
_DUMMY_KEY = b"\x00" * 32


def _sig(key: bytes, client: str) -> str:
    return hmac.new(key, client.encode("utf-8"), _DIGEST).hexdigest()


class AuthError(ValueError):
    """Keyring misuse (unknown/duplicate key id) — an operator error, never
    the verdict path (bad TOKENS shed typed, they do not raise)."""


class SessionKeyring:
    """Named HMAC keys with one minting key and an accepted set (see
    module doc).  ``keys`` maps key id -> secret bytes; ``minting``
    defaults to the first (sorted) key id."""

    def __init__(self, keys: Dict[str, bytes],
                 minting: Optional[str] = None) -> None:
        if not keys:
            raise AuthError("a keyring needs at least one key")
        self._keys: Dict[str, bytes] = {
            str(k): bytes(v) for k, v in keys.items()
        }
        self._minting = minting if minting is not None else sorted(self._keys)[0]
        if self._minting not in self._keys:
            raise AuthError(f"minting key {self._minting!r} not in keyring")
        self.verified = 0
        self.rejected = 0
        self.rotations = 0

    # -- lifecycle ------------------------------------------------------------

    @property
    def minting_key_id(self) -> str:
        return self._minting

    def key_ids(self) -> List[str]:
        return sorted(self._keys)

    def rotate(self, kid: str, secret: bytes) -> None:
        """Install ``kid`` as the NEW minting key.  Every previously
        accepted key stays accepted — tokens minted before the rotation
        keep verifying, so no live session drops."""
        kid = str(kid)
        if kid in self._keys:
            raise AuthError(f"key {kid!r} already in keyring")
        self._keys[kid] = bytes(secret)
        self._minting = kid
        self.rotations += 1

    def retire(self, kid: str) -> None:
        """Remove ``kid`` from the accepted set (its tokens start shedding
        ``unauthorized``).  The minting key cannot be retired — rotate
        first."""
        kid = str(kid)
        if kid == self._minting:
            raise AuthError("cannot retire the minting key; rotate first")
        if kid not in self._keys:
            raise AuthError(f"unknown key {kid!r}")
        del self._keys[kid]

    # -- tokens ---------------------------------------------------------------

    def mint(self, client: str) -> str:
        """A session token for ``client`` under the current minting key."""
        return f"{self._minting}.{_sig(self._keys[self._minting], client)}"

    def verify(self, client: str, token: Optional[str]) -> bool:
        """Whether ``token`` authorizes ``client``.  Never raises on bad
        input — a malformed token is just unauthorized."""
        if not token or "." not in token:
            self.rejected += 1
            return False
        kid, _, sig = token.partition(".")
        key = self._keys.get(kid, _DUMMY_KEY)
        ok = hmac.compare_digest(_sig(key, client), sig) and kid in self._keys
        if ok:
            self.verified += 1
        else:
            self.rejected += 1
        return ok

    def snapshot(self) -> Dict:
        """JSON-serializable keyring state — key IDS only, never secrets
        (``/serve.json`` auth section; golden-shape pinned)."""
        return {
            "keys": self.key_ids(),
            "minting": self._minting,
            "verified": self.verified,
            "rejected": self.rejected,
            "rotations": self.rotations,
        }
