"""Cross-tenant fused dispatch: many muxes, one staged program per window.

A host serving N tenants as N standalone :class:`~.mux.SessionMux`
instances pays N device dispatch floors per batching window — the ~11 ms
dispatch overhead the PR-8 fused pipeline amortizes WITHIN a session
comes right back ACROSS sessions.  :class:`FusedMuxGroup` closes that
gap: a :class:`~..plan.fusion.FusionGroup` assigns every tenant a
disjoint doc-row range of a shared device lane (one
:class:`~..parallel.streaming.StreamingMerge` per storage layout —
``static_rounds`` for padded lanes, the fused pipeline for
paged/ragged ones), each
tenant keeps its OWN :class:`SessionMux` — own
:class:`~.admission.AdmissionController`, own verdict accounting, own
patch stream — and the group recomposes the mux's split round pump
(``_take_batch`` / ``_ingest_batch`` / ``_settle_batch``) around ONE
``drain()`` per touched lane per window.

Isolation is structural, not filtered: tenants never share doc rows, so
a tenant's patches/digests are computed from rows no other tenant can
write, and admission verdicts come from per-tenant controllers that
never see another tenant's load.  Byte equality with the unfused path
holds per tenant by construction (documents are independent CRDTs) and
is pinned by the fuzz suite and asserted in-row by the
``serve_multitenant`` bench.

The WINDOW is owned here: the group's :class:`~.mux.BatchWindowTuner`
times the shared round, any member's backpressure force-closes the
window for everyone (a queue above its high watermark must drain NOW),
and ``FusionGroup.window_rows`` decides whether the drain ships the
multi-tenant offset-plane form (few active tenants: stage only their
blocks) or full-lane staging (every tenant active: strictly cheaper).
Wall-clock reads are legal in this module (serve tier, outside
graftlint's merge scope) — the plan-scope :mod:`~..plan.fusion` stays
clock-free by keeping all timing HERE.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import Counters, GLOBAL_COUNTERS
from ..obs.latency import CLOSE_BACKPRESSURE, CLOSE_FLUSH, CLOSE_WINDOW
from ..obs.timeseries import GLOBAL_HISTORY
from ..parallel.streaming import StreamingMerge
from ..plan.fusion import FusionGroup, LanePlan, TenantSpec
from .admission import AdmissionController, Verdict
from .mux import BatchWindowTuner, SessionMux


def default_lane_factory(actors: Sequence[str],
                         **session_kw) -> Callable[[LanePlan], StreamingMerge]:
    """A ``session_factory`` for :class:`FusedMuxGroup`: one
    :class:`StreamingMerge` per lane, sized to the lane's doc budget,
    storage layout taken from the lane plan.  Padded lanes ride the
    ``static_rounds`` one-shape discipline; paged/ragged lanes (whose
    storage tier forbids ``static_rounds``) ride the fused
    device-resident pipeline instead — either way one window commits as
    staged fused programs, not per-round dispatches."""

    def build(plan: LanePlan) -> StreamingMerge:
        sess = StreamingMerge(
            num_docs=plan.docs, actors=actors,
            static_rounds=(plan.layout == "padded"),
            layout=plan.layout, **session_kw,
        )
        sess.fused_pipeline = True
        return sess

    return build


class FusedMuxGroup:
    """N tenants' serving muxes fused onto shared device lanes.

    ``tenants`` are :class:`~..plan.fusion.TenantSpec`s;
    ``session_factory`` builds one backing session per
    :class:`~..plan.fusion.LanePlan` (see :func:`default_lane_factory`).
    Each tenant's mux is reachable via :meth:`mux` and behaves exactly
    like a standalone one for submit/patches/verdicts — only
    :meth:`pump` timing is shared.  When the lane sessions run on a mesh,
    pass ``shard_rows`` (the lane's rows-per-shard) so tenant row ranges
    never straddle a shard mid-boundary.
    """

    def __init__(
        self,
        tenants: Sequence[TenantSpec],
        session_factory: Callable[[LanePlan], StreamingMerge],
        *,
        lane_capacity: int = 4096,
        shard_rows: Optional[int] = None,
        admission_factory: Optional[Callable[[], AdmissionController]] = None,
        tuner: Optional[BatchWindowTuner] = None,
        degrade_after: int = 8,
        clock: Callable[[], float] = time.monotonic,
        counters: Optional[Counters] = None,
        host: str = "local",
    ) -> None:
        self.group = FusionGroup(tenants, lane_capacity=lane_capacity,
                                 shard_rows=shard_rows)
        self.clock = clock
        self.host = host
        self.counters = counters if counters is not None else GLOBAL_COUNTERS
        #: the SHARED round-open window: one tuner over the fused rounds
        #: (a member's private tuner still tracks its own settle walls)
        self.tuner = tuner if tuner is not None else BatchWindowTuner()
        self._lane_sessions: List[StreamingMerge] = []
        for plan in self.group.lanes:
            sess = session_factory(plan)
            if sess.num_docs < plan.docs:
                raise ValueError(
                    f"lane {plan.lane} session has {sess.num_docs} docs, "
                    f"plan needs {plan.docs}"
                )
            static = getattr(sess, "static_rounds", False)
            fused = getattr(sess, "fused_pipeline", False)
            if plan.layout == "padded" and not static:
                raise ValueError(
                    f"lane {plan.lane} session must be static_rounds: the "
                    "multi-tenant staged form is a one-shape discipline"
                )
            if not (static or fused):
                raise ValueError(
                    f"lane {plan.lane} session must run the fused pipeline: "
                    "a per-round-dispatch lane pays back the dispatch floor "
                    "fusion exists to amortize"
                )
            self._lane_sessions.append(sess)
        self.muxes: Dict[str, SessionMux] = {}
        for name in sorted(self.group.slots):
            slot = self.group.slots[name]
            mux = SessionMux(
                self._lane_sessions[slot.lane],
                admission=(admission_factory() if admission_factory
                           else AdmissionController()),
                tuner=BatchWindowTuner(),
                degrade_after=degrade_after,
                clock=clock,
                counters=self.counters,
                host=f"{host}/{name}",
                doc_base=slot.doc_base,
                doc_capacity=slot.docs,
            )
            mux._fusion_stats = self.fusion_snapshot
            self.muxes[name] = mux
        #: deterministic pump order — sorted tenant names, never arrival
        self._order: Tuple[str, ...] = tuple(sorted(self.muxes))
        self.windows = 0
        self.dispatches = 0
        self._docs_dispatched = 0
        self._occ_sum = 0.0
        self._occ_count = 0
        #: the history plane's occupancy channel (swap in a private plane
        #: the way tests swap ``latency_plane``); disarmed it costs one
        #: attribute read per lane per window
        self.history = GLOBAL_HISTORY

    # -- per-tenant delegation --------------------------------------------

    def mux(self, tenant: str) -> SessionMux:
        m = self.muxes.get(tenant)
        if m is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        return m

    def open_session(self, tenant: str, client: str,
                     token: Optional[str] = None):
        return self.mux(tenant).open_session(client, token=token)

    def submit(self, tenant: str, session_id: int, frame: bytes,
               token: Optional[str] = None) -> Verdict:
        return self.mux(tenant).submit(session_id, frame, token=token)

    def submit_changes(self, tenant: str, session_id: int, changes,
                       token: Optional[str] = None) -> Verdict:
        return self.mux(tenant).submit_changes(session_id, changes,
                                               token=token)

    def patches(self, tenant: str, session_id: int):
        return self.mux(tenant).patches(session_id)

    def read(self, tenant: str, session_id: int):
        return self.mux(tenant).read(session_id)

    # -- the fused round pump ---------------------------------------------

    def window_seconds(self) -> float:
        return self.tuner.window_seconds()

    def window_expired(self) -> bool:
        """Whether the SHARED window should close: measured from the
        earliest member's open mark (first arrival anywhere opens the
        group window), force-closed by any member's backpressure."""
        opened = None
        for name in self._order:
            m = self.muxes[name]
            if not m._buffer:
                continue
            if m.admission.backpressure:
                return True
            if m._window_opened is not None and (
                    opened is None or m._window_opened < opened):
                opened = m._window_opened
        if opened is None:
            return False
        return (self.clock() - opened) >= self.tuner.window_seconds()

    def pump(self, force: bool = False) -> int:
        """Close the shared window (if expired or ``force``) and commit
        every member's buffered round through ONE drain per touched
        lane: take all batches first (no member's ingest reopens another
        member's timing), ingest per lane under the lane's
        ``fusion_rows`` extents, drain once, then settle each member
        with the shared wall.  Returns total frames applied."""
        if not any(self.muxes[n]._buffer for n in self._order):
            return 0
        if not (force or self.window_expired()):
            return 0
        # the SHARED close cause (one window, one cause for every rider):
        # a forced flush, else any member's backpressure, else the window
        # elapsing — read before the drains release backpressure.  Only
        # consulted when some member's latency plane is armed.
        armed = any(self.muxes[n].latency_plane.enabled for n in self._order)
        cause = CLOSE_WINDOW
        if armed:
            if force:
                cause = CLOSE_FLUSH
            elif any(m.admission.backpressure
                     for m in self.muxes.values() if m._buffer):
                cause = CLOSE_BACKPRESSURE
        per_lane: Dict[int, List[Tuple[str, list]]] = {}
        for name in self._order:
            m = self.muxes[name]
            if m._buffer:
                lane = self.group.slots[name].lane
                per_lane.setdefault(lane, []).append((name, m._take_batch()))
        applied = 0
        t_open = self.clock()
        d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
        for lane in sorted(per_lane):
            entries = per_lane[lane]
            sess = self._lane_sessions[lane]
            active = [name for name, _ in entries]
            t0 = self.clock()
            sess.fusion_rows = self.group.window_rows(lane, active)
            try:
                for name, batch in entries:
                    self.muxes[name]._ingest_batch(batch)
                t_staged = self.clock() if armed else None
                sess.drain()
            finally:
                sess.fusion_rows = None
            t1 = self.clock()
            wall = max(0.0, t1 - t0)
            for name, batch in entries:
                # each rider's stage watermarks are the LANE's: the close
                # is the lane round's open, staging/commit are shared —
                # a rider pays the fused window it rode, exactly like the
                # settle wall
                self.muxes[name]._settle_batch(
                    batch, wall, t1,
                    close=t0 if armed else None,
                    staged=t_staged, cause=cause,
                )
                applied += len(batch)
            docs = sum(self.group.slots[name].docs for name in active)
            self._docs_dispatched += docs
            occ = self.group.window_occupancy(lane, active)
            self._occ_sum += occ
            self._occ_count += 1
            if self.history.enabled:
                # the closed planner loop's raw material: one occupancy
                # row per lane per committed window
                self.history.record_occupancy(lane, occ, docs=docs)
        self.dispatches += int(
            GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0
        )
        self.windows += 1
        self.tuner.observe(max(0.0, self.clock() - t_open))
        self.counters.add("serve.fused_windows")
        return applied

    def flush(self) -> int:
        """Force-close the shared window (shutdown, test sync points,
        end-of-rung drains)."""
        return self.pump(force=True)

    # -- health ------------------------------------------------------------

    def fusion_snapshot(self) -> Dict:
        """The shared ``fusion`` section every member's ``/serve.json``
        reports (same key set as the standalone identity report)."""
        return {
            "grouped": True,
            "tenants": len(self.muxes),
            "lanes": len(self.group.lanes),
            "windows": self.windows,
            "dispatches": self.dispatches,
            "docs_per_dispatch": round(
                self._docs_dispatched / self.dispatches, 2
            ) if self.dispatches else 0.0,
            "window_occupancy": round(
                self._occ_sum / self._occ_count, 4
            ) if self._occ_count else 0.0,
        }

    def snapshot(self) -> Dict:
        """The group's own scrape body: the fusion stats, the lane plan,
        the shared window, and every member's full mux snapshot."""
        return {
            "host": self.host,
            "fusion": self.fusion_snapshot(),
            "plan": self.group.to_json(),
            "window": self.tuner.snapshot(),
            "tenants": {
                name: self.muxes[name].snapshot() for name in self._order
            },
        }
