"""Sustained open-loop traffic: the serving tier's load generator.

**Open loop** is the property that makes the ladder honest: arrival times
are fixed by the offered rate alone (``t_i = i / rate``), never gated on
service completions.  A closed-loop generator (issue → wait → issue) slows
itself down exactly when the server saturates, so it measures the server's
throughput as "whatever the server did" and can never show an SLO
breaking.  An open-loop generator keeps offering, the bounded ingest queue
fills, backpressure engages, verdicts turn to ``delay``/``shed`` — the
breakdown is *visible*, which is what the ladder sweeps for.

:func:`run_open_loop` drives one :class:`~.mux.SessionMux` through one
offered-rate rung and reports the typed-verdict accounting plus the
apply-latency distribution (measured per admitted frame, enqueue to
committed device round).  :func:`sustained_ladder` sweeps ascending rates
until the p99 apply latency breaks the SLO (or verdicts stop being clean)
and reports the highest sustained rate — the ``serve_sustained`` ladder
row's docs/s value.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .admission import ADMIT, DELAY, SHED
from .mux import SessionMux

#: one scheduled arrival: (seconds after start, session id, wire frame)
Arrival = Tuple[float, int, bytes]


def build_arrivals(
    frames_by_session: Dict[int, Sequence[bytes]],
    rate_per_s: float,
    duration_s: float,
) -> List[Arrival]:
    """The open-loop schedule: arrivals at ``i / rate`` round-robined over
    the sessions, each session delivering its own frames in order and
    cycling when exhausted (redelivered frames are duplicate-tolerant —
    the CRDT absorbs them — so a long rung keeps offering real ingest
    work).  Deterministic: no RNG, no clock."""
    if rate_per_s <= 0:
        raise ValueError(f"rate must be positive, got {rate_per_s}")
    sids = sorted(frames_by_session)
    if not sids:
        return []
    n = max(1, int(rate_per_s * duration_s))
    cursor = {sid: 0 for sid in sids}
    out: List[Arrival] = []
    for i in range(n):
        sid = sids[i % len(sids)]
        frames = frames_by_session[sid]
        if not frames:
            continue
        out.append((i / rate_per_s, sid, frames[cursor[sid] % len(frames)]))
        cursor[sid] += 1
    return out


@dataclass
class OpenLoopResult:
    """One rung's evidence: typed-verdict accounting + latency readout."""

    rate_per_s: float
    duration_s: float
    offered: int = 0
    admitted: int = 0
    delayed: int = 0
    shed: int = 0
    applied: int = 0
    shed_reasons: Dict[str, int] = field(default_factory=dict)
    queue_peak: int = 0
    rounds: int = 0
    window_seconds: float = 0.0
    p50_apply_s: float = 0.0
    p95_apply_s: float = 0.0
    p99_apply_s: float = 0.0
    max_apply_s: float = 0.0
    wall_seconds: float = 0.0
    #: the latency plane's per-stage decomposition for this rung (present
    #: when the mux's plane was armed): the server-side stage means that
    #: sit NEXT TO the client-observed percentiles above, so one rung's
    #: JSON carries both sides of the sum-consistency story
    latency: Optional[Dict] = None

    @property
    def clean(self) -> bool:
        """Every offered frame admitted — no backpressure, no shedding."""
        return self.shed == 0 and self.delayed == 0

    def accounted(self) -> bool:
        """The zero-silent-drops identity."""
        return self.offered == self.admitted + self.delayed + self.shed

    def to_json(self) -> Dict:
        return {
            "rate_per_s": round(self.rate_per_s, 2),
            "duration_s": round(self.duration_s, 3),
            "offered": self.offered,
            "admitted": self.admitted,
            "delayed": self.delayed,
            "shed": self.shed,
            "applied": self.applied,
            "shed_reasons": dict(sorted(self.shed_reasons.items())),
            "queue_peak": self.queue_peak,
            "rounds": self.rounds,
            "window_seconds": round(self.window_seconds, 6),
            "p50_apply_ms": round(self.p50_apply_s * 1e3, 3),
            "p95_apply_ms": round(self.p95_apply_s * 1e3, 3),
            "p99_apply_ms": round(self.p99_apply_s * 1e3, 3),
            "max_apply_ms": round(self.max_apply_s * 1e3, 3),
            "wall_seconds": round(self.wall_seconds, 3),
            **({"latency": self.latency} if self.latency is not None else {}),
        }


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def run_open_loop(
    mux: SessionMux,
    arrivals: Sequence[Arrival],
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    drain: bool = True,
    deadline_s: Optional[float] = None,
    read_every: int = 0,
) -> OpenLoopResult:
    """Offer ``arrivals`` open-loop against ``mux`` (see module doc).

    The loop submits every arrival whose time has come (late or not —
    open loop never withholds offered work), pumps the mux's round window
    in between, and sleeps only until the next arrival or window expiry.
    ``drain=True`` flushes the tail after the last arrival so every
    admitted frame's latency is measured.  ``deadline_s`` hard-bounds the
    wall clock (a saturated rung must not run away); past it, remaining
    arrivals still submit back-to-back (their verdicts ARE the evidence)
    but no further sleeping happens.  ``read_every=N`` (0 = never, the
    historical behavior) reads the first session's patch stream after
    every Nth committed pump — the pump→read pattern that marks the
    latency plane's VISIBILITY watermark, so an armed plane's
    time-to-visibility histogram fills during the rung."""
    sched = list(arrivals)
    duration = sched[-1][0] if sched else 0.0
    latencies: List[float] = []
    prev_sink = mux.latency_sink
    mux.latency_sink = latencies
    res = OpenLoopResult(
        rate_per_s=(len(sched) / duration if duration else 0.0),
        duration_s=duration,
    )
    read_sid = sched[0][1] if sched else None
    pumps = 0
    start = clock()
    try:
        i = 0
        while i < len(sched):
            now = clock() - start
            overtime = deadline_s is not None and now > deadline_s
            while i < len(sched) and (sched[i][0] <= now or overtime):
                _, sid, frame = sched[i]
                verdict = mux.submit(sid, frame)
                res.offered += 1
                if verdict.kind == ADMIT:
                    res.admitted += 1
                elif verdict.kind == DELAY:
                    res.delayed += 1
                elif verdict.kind == SHED:
                    res.shed += 1
                    res.shed_reasons[verdict.reason] = (
                        res.shed_reasons.get(verdict.reason, 0) + 1
                    )
                i += 1
            if mux.pump() and read_every > 0 and read_sid is not None:
                pumps += 1
                if pumps % read_every == 0:
                    mux.patches(read_sid)
            if i < len(sched) and not overtime:
                nap = min(
                    max(0.0, sched[i][0] - (clock() - start)),
                    max(0.0005, mux.window_seconds() / 4),
                )
                if nap > 0:
                    sleep(nap)
        if drain:
            mux.flush()
            if read_every > 0 and read_sid is not None:
                # expose the tail flush too: the final commits' visibility
                # must be measured, not left pending
                mux.patches(read_sid)
    finally:
        mux.latency_sink = prev_sink
    res.wall_seconds = clock() - start
    res.applied = len(latencies)
    res.queue_peak = mux.admission.peak_depth
    res.rounds = mux.rounds
    res.window_seconds = mux.window_seconds()
    latencies.sort()
    res.p50_apply_s = _pct(latencies, 0.50)
    res.p95_apply_s = _pct(latencies, 0.95)
    res.p99_apply_s = _pct(latencies, 0.99)
    res.max_apply_s = latencies[-1] if latencies else 0.0
    plane = getattr(mux, "latency_plane", None)
    if plane is not None and plane.enabled and plane.records:
        res.latency = plane.decomposition()
    return res


@dataclass
class LadderRung:
    """One swept rate plus whether it sustained the SLO."""

    rate_per_s: float
    result: OpenLoopResult
    slo_p99_s: float
    sustained: bool

    def to_json(self) -> Dict:
        return {
            "rate_per_s": round(self.rate_per_s, 2),
            "sustained": self.sustained,
            "slo_p99_ms": round(self.slo_p99_s * 1e3, 3),
            **self.result.to_json(),
        }


def sustained_ladder(
    mux_factory: Callable[[], Tuple[SessionMux, Dict[int, Sequence[bytes]]]],
    rates: Sequence[float],
    slo_p99_s: float,
    duration_s: float = 1.0,
    delayed_tolerance: float = 0.01,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    warmup: int = 0,
) -> Tuple[List[LadderRung], Optional[LadderRung]]:
    """Sweep ascending arrival rates until the SLO breaks.

    ``mux_factory`` builds a FRESH mux (and its per-session frame lists)
    per rung, so one saturated rung's backlog cannot poison the next; XLA
    compile caching keeps rebuilds cheap when every rung shares shapes.
    A rung sustains iff it shed nothing, delayed at most
    ``delayed_tolerance`` of offered frames, and held p99 apply latency
    within ``slo_p99_s``.  The sweep stops at the first unsustained rung
    (its evidence is recorded — the ladder row shows WHERE it broke).
    ``warmup=N`` runs each rung N times uncounted on throwaway muxes
    first: a rung's batch-size pattern can mint fresh XLA program variants
    (round-width buckets, slot-window buckets, fused drain depths), and a
    compile landing inside a measured percentile would break the SLO for
    the wrong reason — the compile cache is process-wide, so the measured
    pass runs warm.  Returns ``(all rungs, highest sustained rung or
    None)``."""
    rungs: List[LadderRung] = []
    best: Optional[LadderRung] = None
    for rate in rates:
        deadline = max(duration_s * 4, duration_s + 2.0)
        for _ in range(max(0, warmup)):
            wmux, wframes = mux_factory()
            run_open_loop(
                wmux, build_arrivals(wframes, rate, duration_s),
                clock=clock, sleep=sleep, deadline_s=deadline,
            )
        mux, frames_by_session = mux_factory()
        arrivals = build_arrivals(frames_by_session, rate, duration_s)
        res = run_open_loop(
            mux, arrivals, clock=clock, sleep=sleep,
            deadline_s=deadline,
        )
        ok = (
            res.accounted()
            and res.shed == 0
            and res.delayed <= delayed_tolerance * max(1, res.offered)
            and res.p99_apply_s <= slo_p99_s
        )
        rung = LadderRung(
            rate_per_s=rate, result=res, slo_p99_s=slo_p99_s, sustained=ok,
        )
        rungs.append(rung)
        if ok:
            best = rung
        else:
            break
    return rungs, best
