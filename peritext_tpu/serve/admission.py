"""Admission control: typed verdicts over a bounded ingest queue.

The serving tier's first obligation is the one GPUOS-style multiplexing
papers keep re-deriving: when many tenants share one accelerator, the
multiplexer must decide *explicitly* what happens to work it cannot take —
an implicit decision is a silent drop, and a CRDT fleet built on silent
drops converges to the wrong document.  Every submission therefore gets a
typed :class:`Verdict`:

* ``admit`` — the op entered the bounded ingest queue and WILL be applied
  in an upcoming device round;
* ``delay(hint)`` — backpressure: the queue is above its high watermark;
  nothing was enqueued, and ``hint_seconds`` tells the client when a retry
  is likely to admit (derived from the queue's observed drain rate);
* ``shed(reason)`` — overload: the queue is full (or the session is over
  its per-session quota); nothing was enqueued, and ``reason`` is a typed
  label the client, the chaos oracle and the ``peritext_serve_*`` gauges
  all agree on.

Backpressure is watermark-driven with hysteresis: crossing the HIGH
watermark starts delaying, and delaying stops only once the queue drains
below the LOW watermark — without the gap, a queue hovering at the
threshold would flap between admit and delay every round.

The per-session quota is where overload degradation meets the PR-1
quarantine/fallback ladder: one hot session may not starve the other
tenants of queue space, so its overflow sheds with ``session-quota`` — and
the :class:`~.mux.SessionMux` responds to SUSTAINED quota shedding by
demoting that session's doc to scalar-replay fallback (degraded but
correct, off the device round path) rather than shedding its writes
forever.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import Counters, GLOBAL_COUNTERS

#: verdict kinds
ADMIT = "admit"
DELAY = "delay"
SHED = "shed"

#: typed shed reasons — the vocabulary the chaos oracle and the exporters
#: share (a shed with an unknown reason is a bug, not a new category)
SHED_QUEUE_FULL = "queue-full"
#: SUSTAINED overload: backpressure delays kept coming and the queue never
#: drained below the high watermark — ingest truly outruns device rounds,
#: so delays escalate to sheds until the queue drains (see offer())
SHED_OVERLOAD = "overload"
SHED_SESSION_QUOTA = "session-quota"
SHED_UNKNOWN_SESSION = "unknown-session"
SHED_CAPACITY = "capacity"
#: the session's doc has been demoted off the device path AND its scalar
#: backlog is saturated too — the ladder's last rung still answers typed
SHED_DEGRADED = "degraded"
#: the doc's serving host died and failover could not (yet) re-place it —
#: the fleet tier's typed answer while durable state is being re-homed, or
#: terminally when no live host has capacity.  Ops shed here are retryable:
#: nothing about the doc's durable state was lost (checkpoint + journal)
SHED_FAILOVER = "failover"
#: per-session wire auth: the submission carried a missing/bad HMAC session
#: token (serve/auth.SessionKeyring) — rejected AT admission, before any
#: queue space or doc slot is touched
SHED_UNAUTHORIZED = "unauthorized"

SHED_REASONS = (
    SHED_QUEUE_FULL,
    SHED_OVERLOAD,
    SHED_SESSION_QUOTA,
    SHED_UNKNOWN_SESSION,
    SHED_CAPACITY,
    SHED_DEGRADED,
    SHED_FAILOVER,
    SHED_UNAUTHORIZED,
)


@dataclass(frozen=True)
class Verdict:
    """One submission's typed outcome (see module doc)."""

    kind: str
    #: typed shed reason (``kind == "shed"`` only)
    reason: Optional[str] = None
    #: suggested client retry delay (``kind == "delay"`` only)
    hint_seconds: Optional[float] = None
    #: queue depth observed at decision time (telemetry; all kinds)
    queue_depth: int = 0

    @property
    def admitted(self) -> bool:
        return self.kind == ADMIT

    def to_json(self) -> Dict:
        out: Dict = {"kind": self.kind, "queue_depth": self.queue_depth}
        if self.reason is not None:
            out["reason"] = self.reason
        if self.hint_seconds is not None:
            out["hint_seconds"] = round(self.hint_seconds, 4)
        return out


@dataclass
class AdmissionStats:
    """Cumulative verdict accounting.  The zero-silent-drops invariant is
    ``submitted == admitted + delayed + shed`` — checked by the chaos
    harness under composed overload + partition."""

    submitted: int = 0
    admitted: int = 0
    delayed: int = 0
    shed: int = 0
    shed_reasons: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> Dict:
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "delayed": self.delayed,
            "shed": self.shed,
            "shed_reasons": dict(sorted(self.shed_reasons.items())),
        }


class AdmissionController:
    """Bounded ingest queue with watermark backpressure (see module doc).

    ``max_depth`` bounds the queue in admission units (frames by default;
    pass ``cost`` to weigh heavier submissions).  ``high_watermark`` /
    ``low_watermark`` are fractions of ``max_depth``; ``session_quota`` is
    the per-session share of ``max_depth`` one tenant may hold (None =
    unlimited).  ``shed_after`` is the delay→shed escalation ladder: a
    transient burst gets ``delay`` verdicts, but once ``shed_after``
    consecutive offers have been delayed with the queue still pinned above
    the watermarks, ingest is provably outrunning device rounds and
    verdicts escalate to typed ``shed(overload)`` until the queue drains —
    a client retrying a delay forever must eventually learn the overload
    is sustained.  Thread-safe: submit paths and the round pump may run on
    different threads.
    """

    def __init__(
        self,
        max_depth: int = 1024,
        high_watermark: float = 0.75,
        low_watermark: float = 0.5,
        session_quota: Optional[float] = 0.5,
        shed_after: int = 16,
        counters: Optional[Counters] = None,
    ) -> None:
        if max_depth <= 0:
            raise ValueError(f"max_depth must be positive, got {max_depth}")
        if not 0.0 < low_watermark <= high_watermark <= 1.0:
            raise ValueError(
                "watermarks must satisfy 0 < low <= high <= 1, got "
                f"low={low_watermark} high={high_watermark}"
            )
        self.max_depth = int(max_depth)
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.session_quota = (
            None if session_quota is None else float(session_quota)
        )
        self.shed_after = int(shed_after)
        self.counters = counters if counters is not None else GLOBAL_COUNTERS
        self._lock = threading.Lock()
        self._depth = 0
        self._peak_depth = 0
        #: consecutive delay verdicts since the last admit/drain — the
        #: sustained-overload escalation input
        self._delay_streak = 0
        self._per_session: Dict[int, int] = {}
        #: hysteresis latch: True between crossing the high watermark and
        #: draining back below the low one
        self._backpressure = False
        self.stats = AdmissionStats()
        #: rolling drain-rate estimate (units applied per second) behind the
        #: delay hint; fed by :meth:`observe_drain`
        self._drain_rate: float = 0.0
        #: per-session ring of recent verdicts — the incident context a
        #: quarantine/rollback flight dump appends (see verdict_tail());
        #: bounded per session AND in tracked sessions
        self._tails: Dict[int, deque] = {}
        self._tail_len = 32
        self._tail_sessions = 1024

    # -- decision ------------------------------------------------------------

    def offer(self, session_id: int, cost: int = 1,
              degraded: bool = False) -> Verdict:
        """Decide one submission.  ``admit`` reserves ``cost`` units of
        queue space (released by :meth:`mark_applied`); any other verdict
        reserves nothing.  ``degraded`` marks a session already demoted to
        scalar fallback: its work bypasses the device round budget, so it
        admits below the high watermark regardless of its quota — but a
        saturated queue still sheds it with the typed ``degraded`` reason."""
        cost = max(1, int(cost))
        with self._lock:
            self.stats.submitted += 1
            depth = self._depth
            if depth + cost > self.max_depth:
                v = self._shed_locked(
                    SHED_DEGRADED if degraded else SHED_QUEUE_FULL, depth
                )
                self._note_verdict_locked(session_id, v)
                return v
            held = self._per_session.get(session_id, 0)
            if (
                not degraded
                and self.session_quota is not None
                and held + cost > self.session_quota * self.max_depth
            ):
                # one hot tenant may not starve the rest of the queue; the
                # mux converts SUSTAINED quota sheds into a fallback
                # demotion (the degradation ladder), so this reason is a
                # transition state, not a permanent write loss
                v = self._shed_locked(SHED_SESSION_QUOTA, depth)
                self._note_verdict_locked(session_id, v)
                return v
            high = self.high_watermark * self.max_depth
            if depth + cost > high:
                self._backpressure = True
            elif self._backpressure and depth <= self.low_watermark * self.max_depth:
                self._backpressure = False
                self._delay_streak = 0
            if self._backpressure and not degraded:
                self._delay_streak += 1
                if self._delay_streak > self.shed_after:
                    # sustained: the queue has not drained through a whole
                    # ladder of delays — escalate to a typed shed so the
                    # client knows this is overload, not a blip
                    v = self._shed_locked(SHED_OVERLOAD, depth)
                    self._note_verdict_locked(session_id, v)
                    return v
                self.stats.delayed += 1
                self.counters.add("serve.delayed")
                v = Verdict(
                    kind=DELAY,
                    hint_seconds=self._delay_hint_locked(),
                    queue_depth=depth,
                )
                self._note_verdict_locked(session_id, v)
                return v
            if not degraded:
                # degraded-session admits bypass backpressure entirely, so
                # they say nothing about whether delayed clients' work is
                # draining — only a normal admit (or a drain below the low
                # watermark) may reset the delay→shed escalation
                self._delay_streak = 0
            self._depth = depth + cost
            self._peak_depth = max(self._peak_depth, self._depth)
            self._per_session[session_id] = held + cost
            self.stats.admitted += 1
            self.counters.add("serve.admitted")
            v = Verdict(kind=ADMIT, queue_depth=self._depth)
            self._note_verdict_locked(session_id, v)
            return v

    def shed_out_of_band(self, reason: str) -> Verdict:
        """Record a typed shed decided OUTSIDE the queue logic (unknown
        session, doc-slot capacity): it still counts as a submission so
        the zero-silent-drops identity covers every client request, and
        it still lands in the verdict stats the exporters and the ``obs
        serve`` health check read."""
        with self._lock:
            self.stats.submitted += 1
            return self._shed_locked(reason, self._depth)

    def delay_out_of_band(self, hint_seconds: float = 0.05) -> Verdict:
        """Record a typed delay decided OUTSIDE the queue logic — the fleet
        tier's "this doc is mid-failover/mid-cutover, retry shortly"
        verdict.  Counts as a submission so the zero-silent-drops identity
        covers it, exactly like :meth:`shed_out_of_band`."""
        with self._lock:
            self.stats.submitted += 1
            self.stats.delayed += 1
            self.counters.add("serve.delayed")
            return Verdict(
                kind=DELAY, hint_seconds=float(hint_seconds),
                queue_depth=self._depth,
            )

    def _shed_locked(self, reason: str, depth: int) -> Verdict:
        self.stats.shed += 1
        self.stats.shed_reasons[reason] = (
            self.stats.shed_reasons.get(reason, 0) + 1
        )
        self.counters.add("serve.shed")
        self.counters.add(f"serve.shed.{reason}")
        return Verdict(kind=SHED, reason=reason, queue_depth=depth)

    def _note_verdict_locked(self, session_id: int, verdict: Verdict) -> None:
        """Ring one verdict into the session's tail (post-mortem context;
        see :meth:`verdict_tail`).  The submission index doubles as the
        tail entry's sequence number."""
        tail = self._tails.get(session_id)
        if tail is None:
            if len(self._tails) >= self._tail_sessions:
                # evict the oldest-tracked session wholesale: tails exist
                # for post-mortems on ACTIVE docs, not as a history of
                # every session id ever offered
                self._tails.pop(next(iter(self._tails)))
            tail = self._tails[session_id] = deque(maxlen=self._tail_len)
        tail.append({"seq": self.stats.submitted, **verdict.to_json()})

    def verdict_tail(self, session_id: int) -> List[Dict]:
        """The session's recent verdicts, oldest first — what a
        quarantine/rollback flight dump appends as incident context (the
        backpressure picture around the fault)."""
        with self._lock:
            return list(self._tails.get(session_id, ()))

    def _delay_hint_locked(self) -> float:
        """How long until a retry is likely to admit: the units above the
        low watermark divided by the observed drain rate.  With no drain
        observed yet the hint is one nominal round (conservative but
        finite — a client must never be told to wait forever)."""
        excess = self._depth - self.low_watermark * self.max_depth
        if self._drain_rate > 0 and excess > 0:
            return max(0.001, excess / self._drain_rate)
        return 0.05

    # -- the round pump's side ----------------------------------------------

    def mark_applied(self, session_id: int, cost: int = 1) -> None:
        """Release queue space a committed device round drained."""
        cost = max(1, int(cost))
        with self._lock:
            self._depth = max(0, self._depth - cost)
            held = self._per_session.get(session_id, 0) - cost
            if held > 0:
                self._per_session[session_id] = held
            else:
                self._per_session.pop(session_id, None)
            if self._backpressure and (
                self._depth <= self.low_watermark * self.max_depth
            ):
                self._backpressure = False
                self._delay_streak = 0

    def observe_drain(self, units: int, seconds: float) -> None:
        """Teach the delay hint this round's drain rate (EWMA)."""
        if seconds <= 0 or units <= 0:
            return
        rate = units / seconds
        with self._lock:
            self._drain_rate = (
                rate if self._drain_rate == 0
                else 0.7 * self._drain_rate + 0.3 * rate
            )

    # -- readout -------------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    @property
    def peak_depth(self) -> int:
        with self._lock:
            return self._peak_depth

    @property
    def backpressure(self) -> bool:
        with self._lock:
            return self._backpressure

    def session_depth(self, session_id: int) -> int:
        with self._lock:
            return self._per_session.get(session_id, 0)

    def snapshot(self) -> Dict:
        """JSON-serializable queue + verdict state (``/serve.json`` body
        section; the golden-shape test pins these keys)."""
        with self._lock:
            return {
                "depth": self._depth,
                "peak": self._peak_depth,
                "max_depth": self.max_depth,
                "high_watermark": self.high_watermark,
                "low_watermark": self.low_watermark,
                "shed_after": self.shed_after,
                "backpressure": self._backpressure,
                "drain_rate_per_s": round(self._drain_rate, 3),
                "verdicts": self.stats.to_json(),
            }
