"""Session multiplexing: many client sessions on one device pipeline.

A :class:`SessionMux` is the serving tier's front door for ONE host: it
maps client sessions onto a :class:`~..parallel.streaming.StreamingMerge`'s
slot buckets, runs admission control over a bounded ingest queue
(:mod:`.admission`), batches admitted frames into device rounds on an
autotuned round-open window, and hands each session back its incremental
``Patch`` stream — the same ``InputOperation``/``Patch`` vocabulary the
ProseMirror bridge speaks, so an editor client cannot tell the mux from a
direct session.

**The round-open window** is the latency/occupancy dial: the mux collects
arrivals for ``window`` seconds before closing a round, so a longer window
means fuller padded op streams (better padding efficiency — the
bucket-occupancy tables' metric) at the cost of per-op latency.
:class:`BatchWindowTuner` picks it from the rolling round-latency
percentile exactly the way the PR-3 supervisor picks its watchdog
deadline — ``clamp(margin * rolling_p99(round_seconds), floor, ceiling)``
— but clamps to the FLOOR when empty (lowest latency is the safe direction
for a batching window; the deadline autotuner's empty-clamp is the
ceiling, the safe direction for a watchdog).  The derivation: dispatching
rounds faster than the device retires them only queues dispatches, so the
window tracks what a round actually costs; a low-rate tenant mix produces
cheap rounds and the window collapses to the floor (interactive latency),
a saturating mix produces expensive rounds and the window stretches toward
the ceiling (batch occupancy).  The window-movement test pins exactly that
divergence.

**Degradation** integrates the PR-1 quarantine/fallback ladder: a session
whose quota sheds persist for ``degrade_after`` consecutive submissions is
demoted via ``force_fallback`` (scalar replay — degraded but correct, off
the device round budget) and its writes keep flowing; shedding is a
pressure signal, never a silent write loss.

Wall-clock reads are legal here (``serve/`` is outside graftlint's PTL006
merge scope), but every read goes through the injected ``clock`` callable
so tests drive the window deterministically.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.types import Change, Patch
from ..obs import Counters, GLOBAL_COUNTERS, GLOBAL_HISTOGRAMS, Histogram
from ..obs.latency import (
    CLOSE_BACKPRESSURE,
    CLOSE_FLUSH,
    CLOSE_WINDOW,
    GLOBAL_LATENCY,
)
from ..obs.timeseries import GLOBAL_HISTORY
from ..parallel.codec import encode_frame
from ..parallel.streaming import REASON_CAPACITY, StreamingMerge
from .admission import (
    ADMIT,
    AdmissionController,
    SHED,
    SHED_CAPACITY,
    SHED_SESSION_QUOTA,
    SHED_UNAUTHORIZED,
    SHED_UNKNOWN_SESSION,
    Verdict,
)


class BatchWindowTuner:
    """Round-open window from the rolling round-latency percentile.

    ``window_seconds() == clamp(margin * rolling_p{quantile}(round wall),
    floor, ceiling)``; empty clamps to ``floor`` (see module doc for why
    the empty direction inverts the supervisor's).  Observations come from
    the mux's own committed rounds (measured around ``session.drain()``),
    so the tuner adapts to THIS host's device and workload, not a global
    histogram another session may be feeding.
    """

    def __init__(
        self,
        floor: float = 0.002,
        ceiling: float = 0.25,
        margin: float = 1.0,
        quantile: float = 0.99,
        window: int = 64,
    ) -> None:
        if not 0 < floor <= ceiling:
            raise ValueError(
                f"need 0 < floor <= ceiling, got {floor}/{ceiling}"
            )
        self.floor = float(floor)
        self.ceiling = float(ceiling)
        self.margin = float(margin)
        self.quantile = float(quantile)
        #: rolling window of recent committed-round walls (private
        #: histogram: the tuner must see THIS mux's rounds only)
        self.round_seconds = Histogram(window=window)

    def observe(self, round_wall_seconds: float) -> None:
        self.round_seconds.observe(round_wall_seconds)

    def window_seconds(self) -> float:
        if self.round_seconds.count == 0:
            return self.floor
        tuned = self.margin * self.round_seconds.percentile(self.quantile)
        return float(min(self.ceiling, max(self.floor, tuned)))

    def snapshot(self) -> Dict:
        return {
            "seconds": round(self.window_seconds(), 6),
            "floor": self.floor,
            "ceiling": self.ceiling,
            "margin": self.margin,
            "quantile": self.quantile,
            "p99_round_seconds": round(
                self.round_seconds.percentile(self.quantile), 6
            ),
            "rounds_observed": self.round_seconds.count,
        }


@dataclass
class ClientSession:
    """One multiplexed client session: a stable id, its doc slot, and its
    verdict/degradation bookkeeping."""

    session_id: int
    client: str
    doc_index: int
    submitted: int = 0
    admitted: int = 0
    shed: int = 0
    delayed: int = 0
    #: consecutive quota sheds — ``degrade_after`` of them demotes the doc
    quota_shed_streak: int = 0
    degraded: bool = False
    closed: bool = False
    extras: Dict = field(default_factory=dict)

    def to_json(self) -> Dict:
        return {
            "client": self.client,
            "doc": self.doc_index,
            "submitted": self.submitted,
            "admitted": self.admitted,
            "delayed": self.delayed,
            "shed": self.shed,
            "degraded": self.degraded,
            "closed": self.closed,
        }


class SessionMux:
    """Multiplexes client sessions onto one streaming device pipeline.

    ``session`` is the backing :class:`StreamingMerge` (its ``num_docs`` is
    the slot budget); sessions claim doc slots append-only — a closed
    session's doc state stays resident (CRDT state is history, not a
    buffer), so slot reuse is a placement concern for the
    :class:`~..parallel.router.FleetRouter`, not the mux.  ``clock`` is
    monotonic seconds (injected for tests).  All submission paths return a
    typed :class:`~.admission.Verdict`; nothing is ever silently dropped.
    """

    def __init__(
        self,
        session: StreamingMerge,
        admission: Optional[AdmissionController] = None,
        tuner: Optional[BatchWindowTuner] = None,
        degrade_after: int = 8,
        clock: Callable[[], float] = time.monotonic,
        counters: Optional[Counters] = None,
        host: str = "local",
        auth=None,
        auth_per_frame: bool = False,
        doc_base: int = 0,
        doc_capacity: Optional[int] = None,
    ) -> None:
        self.session = session
        #: the doc-row slice of ``session`` this mux owns: a standalone mux
        #: owns the whole doc axis; a FusedMuxGroup member owns the
        #: disjoint ``[doc_base, doc_base + doc_capacity)`` range its
        #: LaneSlot assigned — isolation between fused tenants is this
        #: range discipline, never a runtime filter
        self.doc_base = int(doc_base)
        if not 0 <= self.doc_base <= session.num_docs:
            raise ValueError(
                f"doc_base {doc_base} outside session's {session.num_docs} docs"
            )
        self.doc_capacity = (
            int(doc_capacity) if doc_capacity is not None
            else session.num_docs - self.doc_base
        )
        if self.doc_base + self.doc_capacity > session.num_docs:
            raise ValueError(
                f"doc range [{self.doc_base}, "
                f"{self.doc_base + self.doc_capacity}) exceeds session's "
                f"{session.num_docs} docs"
            )
        self.admission = admission if admission is not None else AdmissionController()
        self.tuner = tuner if tuner is not None else BatchWindowTuner()
        #: per-session wire auth (serve/auth.SessionKeyring): when set,
        #: open_session requires a valid HMAC token for the client name —
        #: bad/missing tokens shed typed ``unauthorized`` BEFORE any slot
        #: or queue space is touched.  ``auth_per_frame`` additionally
        #: re-verifies the token on every submit (bearer-session-id alone
        #: stops being enough).  None (default) = open tier, exactly the
        #: pre-auth behavior.
        self.auth = auth
        self.auth_per_frame = bool(auth_per_frame)
        self.degrade_after = int(degrade_after)
        self.clock = clock
        self.counters = counters if counters is not None else GLOBAL_COUNTERS
        self.host = host
        self._sessions: Dict[int, ClientSession] = {}
        self._next_session = 0
        self._next_doc = 0
        #: the open round's buffered admitted frames:
        #: (session_id, doc, frame_bytes, enqueue_clock, submit_clock) —
        #: submit_clock is read at submit() ENTRY (pre-verdict) so the
        #: latency plane can price the admission stage; with the plane
        #: disarmed it equals enqueue_clock (no extra clock read)
        self._buffer: List[Tuple[int, int, bytes, float, float]] = []
        self._window_opened: Optional[float] = None
        self.rounds = 0
        self.applied = 0
        self.degraded_docs = 0
        #: when a list, per-frame apply latencies (enqueue -> committed
        #: round) are appended here — the traffic generator's per-rung
        #: percentile source (the histograms keep the fleet-wide view)
        self.latency_sink: Optional[List[float]] = None
        #: the stage-watermark latency plane this mux feeds (default: the
        #: process-wide one, off until ``GLOBAL_LATENCY.enable()``); bench
        #: arms swap in a private plane so their decompositions don't mix
        self.latency_plane = GLOBAL_LATENCY
        #: the history plane this mux feeds one frame per committed round
        #: (same swap-in-a-private-plane discipline as ``latency_plane``);
        #: disarmed it costs one attribute read per settle
        self.history_plane = GLOBAL_HISTORY
        #: when this mux rides a fused group, the group's
        #: ``fusion_snapshot`` callable — snapshot()'s ``fusion`` key
        #: reports the shared window's stats instead of the standalone
        #: one-dispatch-per-round identity
        self._fusion_stats: Optional[Callable[[], Dict]] = None
        #: shed count at the last committed round — snapshot()'s
        #: ``recent_sheds`` (sheds since the tier last kept up) derives
        #: from it, so a host that shed once during a blip and then ran
        #: clean rounds stops reporting unhealthy (the ``obs serve``
        #: health check reads recency, not the process-lifetime counter)
        self._shed_mark = 0
        # wire the flight-recorder incident-context hook: a quarantine/
        # rollback fault dump on the backing session appends the affected
        # doc's admission-verdict tail (the backpressure picture around
        # the incident)
        recorder = getattr(session, "recorder", None)
        if recorder is not None and hasattr(recorder, "add_context_provider"):
            recorder.add_context_provider(
                "admission-verdicts", self._fault_context
            )

    # -- session lifecycle ----------------------------------------------------

    def open_session(self, client: str,
                     token: Optional[str] = None) -> Tuple[Optional[int], Verdict]:
        """Claim a doc slot for a new client session.  Returns
        ``(session_id, verdict)`` — ``session_id`` is None when the slot
        budget is exhausted (typed ``capacity`` shed; the fleet router's
        cue to place the doc on another host) or, on an auth-enabled mux,
        when ``token`` fails HMAC verification for ``client`` (typed
        ``unauthorized`` shed — checked FIRST, so an unauthorized probe
        never learns whether capacity exists)."""
        if self.auth is not None and not self.auth.verify(client, token):
            return None, self.admission.shed_out_of_band(SHED_UNAUTHORIZED)
        if self._next_doc >= self.doc_capacity:
            return None, self.admission.shed_out_of_band(SHED_CAPACITY)
        sid = self._next_session
        self._next_session += 1
        doc = self.doc_base + self._next_doc
        self._next_doc += 1
        self._sessions[sid] = ClientSession(
            session_id=sid, client=client, doc_index=doc,
        )
        self.counters.add("serve.sessions_opened")
        return sid, Verdict(kind=ADMIT, queue_depth=self.admission.depth)

    def close_session(self, session_id: int) -> None:
        sess = self._sessions.get(session_id)
        if sess is not None and not sess.closed:
            sess.closed = True
            self.counters.add("serve.sessions_closed")

    def sessions(self) -> Dict[int, ClientSession]:
        return dict(self._sessions)

    # -- the ingest surface ---------------------------------------------------

    def submit(self, session_id: int, frame: bytes,
               token: Optional[str] = None) -> Verdict:
        """Submit one wire frame for a session's doc.  ``admit`` buffers it
        into the open round; ``delay``/``shed`` buffer nothing and the
        client owns the retry.  A degraded session's frames are ingested
        IMMEDIATELY on admit (scalar fallback replays host-side; holding
        them for the device window would only add latency to a path that
        no longer batches).  On an ``auth_per_frame`` mux every submit
        must re-present the session's token (sheds ``unauthorized``
        otherwise)."""
        sess = self._sessions.get(session_id)
        if sess is None or sess.closed:
            return self.admission.shed_out_of_band(SHED_UNKNOWN_SESSION)
        if (self.auth is not None and self.auth_per_frame
                and not self.auth.verify(sess.client, token)):
            sess.submitted += 1
            sess.shed += 1
            return self.admission.shed_out_of_band(SHED_UNAUTHORIZED)
        # pre-verdict watermark for the latency plane's admit stage; the
        # disarmed path reads no extra clock (overhead budget)
        t_sub = self.clock() if self.latency_plane.enabled else None
        sess.submitted += 1
        verdict = self.admission.offer(
            session_id, cost=1, degraded=sess.degraded
        )
        if verdict.kind == ADMIT:
            sess.admitted += 1
            sess.quota_shed_streak = 0
            now = self.clock()
            if sess.degraded:
                self.session.ingest_frame(
                    sess.doc_index, frame, on_corrupt="quarantine"
                )
                self.admission.mark_applied(session_id, 1)
                self.applied += 1
            else:
                if self._window_opened is None:
                    self._window_opened = now
                self._buffer.append((
                    session_id, sess.doc_index, frame, now,
                    t_sub if t_sub is not None else now,
                ))
        elif verdict.kind == SHED:
            sess.shed += 1
            if verdict.reason == SHED_SESSION_QUOTA:
                sess.quota_shed_streak += 1
                if (not sess.degraded
                        and sess.quota_shed_streak >= self.degrade_after):
                    self._degrade(sess)
            else:
                sess.quota_shed_streak = 0
        else:
            sess.delayed += 1
        return verdict

    def submit_changes(self, session_id: int,
                       changes: Sequence[Change],
                       token: Optional[str] = None) -> Verdict:
        """The object-boundary form of :meth:`submit`: a batch of
        ``Change`` objects (what ``bridge.Editor.dispatch_input_ops``
        mints from ``InputOperation`` dicts) submitted as one frame."""
        return self.submit(session_id, encode_frame(list(changes)),
                           token=token)

    def _degrade(self, sess: ClientSession) -> None:
        """The quarantine/fallback rung for a hot session: sustained quota
        shedding means the doc's ingest outruns its fair device-round
        share, so it leaves the device path (scalar replay, correct but
        degraded) and its writes keep flowing — typed quarantine evidence
        included, never a silent drop."""
        sess.degraded = True
        self.degraded_docs += 1
        self.counters.add("serve.degraded_sessions")
        self.session.force_fallback(
            sess.doc_index, REASON_CAPACITY,
            "serve: sustained session-quota shedding "
            f"({sess.quota_shed_streak} consecutive)",
        )

    # -- the round pump -------------------------------------------------------

    def window_seconds(self) -> float:
        return self.tuner.window_seconds()

    def window_expired(self) -> bool:
        """Whether the open round should close: its window elapsed, or
        backpressure engaged (a queue above the high watermark must drain
        NOW, not at the window's leisure)."""
        if not self._buffer:
            return False
        if self.admission.backpressure:
            return True
        assert self._window_opened is not None
        return (self.clock() - self._window_opened) >= self.window_seconds()

    def _take_batch(self) -> List[Tuple[int, int, bytes, float, float]]:
        """Close the open round: detach the buffered frames and reset the
        window.  The round-pump's first third, split out so a fused group
        can take EVERY member's batch before any lane drains."""
        batch, self._buffer = self._buffer, []
        self._window_opened = None
        return batch

    def close_cause(self, force: bool) -> str:
        """Why the open round is closing — the typed vocabulary the
        latency plane's force-close counters report.  Read BEFORE the
        drain (the drain itself releases backpressure)."""
        if force:
            return CLOSE_FLUSH
        if self.admission.backpressure:
            return CLOSE_BACKPRESSURE
        return CLOSE_WINDOW

    def _ingest_batch(self, batch: Sequence[Tuple[int, int, bytes, float, float]],
                      ) -> None:
        """Bulk-ingest a taken batch into the backing session (corrupt
        frames quarantine their doc — per-doc fault isolation, never an
        exception out of the serving loop).  No drain: the caller owns
        when the device program runs."""
        self.session.ingest_frames(
            [(doc, frame) for _, doc, frame, _, _ in batch],
            on_corrupt="quarantine",
        )

    def _settle_batch(self, batch: Sequence[Tuple[int, int, bytes, float, float]],
                      wall: float, now: float,
                      close: Optional[float] = None,
                      staged: Optional[float] = None,
                      cause: str = CLOSE_WINDOW) -> None:
        """Account a committed batch after its drain: release queue
        space, feed the window tuner + latency histograms, advance the
        round/apply tallies.  ``wall`` is the committed round's wall (on
        a fused group: the SHARED window's wall — every rider pays the
        window it rode); ``now`` is the commit clock.  ``close``/``staged``
        are the latency plane's window-close and staged watermarks (passed
        only while the plane is armed); the batch record anchors on the
        FIRST buffered frame — the op that waited the whole window, the
        worst case an SLO cares about."""
        self.rounds += 1
        self.applied += len(batch)
        self.tuner.observe(wall)
        self.admission.observe_drain(len(batch), wall)
        for sid, _, _, enq, _ in batch:
            self.admission.mark_applied(sid, 1)
            lat = max(0.0, now - enq)
            GLOBAL_HISTOGRAMS.observe("serve.apply_seconds", lat)
            if self.latency_sink is not None:
                self.latency_sink.append(lat)
        GLOBAL_HISTOGRAMS.observe("serve.round_seconds", wall)
        self.counters.add("serve.rounds")
        self.counters.add("serve.applied_frames", len(batch))
        plane = self.latency_plane
        if (plane.enabled and batch
                and close is not None and staged is not None):
            _, _, _, enq0, sub0 = batch[0]
            mesh = getattr(self.session, "mesh", None)
            plane.observe_batch(
                submit=sub0, admit=enq0, close=close, staged=staged,
                commit=now,
                marks=getattr(self.session, "last_drain_marks", None),
                cause=cause, batch=len(batch),
                shards=int(getattr(mesh, "size", 1) or 1),
            )
        if not self.admission.backpressure:
            # the tier is keeping up again: sheds before this round are
            # history, not current health
            self._shed_mark = self.admission.stats.shed
        if self.history_plane.enabled:
            # one history frame per committed round (the plane's own
            # sample_every decimates); measured by the caller's wall via
            # note_overhead, never by the plane itself
            self.history_plane.advance_round(serve=self)

    def pump(self, force: bool = False) -> int:
        """Close the open round if its window expired (or ``force``) and
        drain it through the device: bulk-ingest the buffered frames,
        run device rounds to empty, release queue space, and feed the
        window tuner + latency histograms.  Returns the number of frames
        applied.  (The take/ingest/settle thirds are split methods so
        :class:`~.fused.FusedMuxGroup` can recompose them around ONE
        shared lane drain.)"""
        if not self._buffer or not (force or self.window_expired()):
            return 0
        armed = self.latency_plane.enabled
        cause = self.close_cause(force) if armed else CLOSE_WINDOW
        batch = self._take_batch()
        t0 = self.clock()
        self._ingest_batch(batch)
        t_staged = self.clock() if armed else None
        self.session.drain()
        t1 = self.clock()
        self._settle_batch(batch, max(0.0, t1 - t0), t1,
                           close=t0 if armed else None,
                           staged=t_staged, cause=cause)
        return len(batch)

    def flush(self) -> int:
        """Force-close the open round regardless of its window (shutdown,
        test sync points, the traffic generator's end-of-rung drain)."""
        return self.pump(force=True)

    def queue_depth(self) -> int:
        return self.admission.depth

    # -- the read surface -----------------------------------------------------

    def patches(self, session_id: int) -> List[Patch]:
        """The session's incremental ``Patch`` stream since its previous
        call (first call builds the doc from empty) — the same vocabulary
        the scalar path and the ProseMirror bridge emit.

        The first read also arms the session's fused digest prefetch:
        this client has PROVEN the pump→read pattern, so from the next
        pump on, every drain pre-dispatches the fused resolve+digest and
        the window's host work hides the round's resolution compute (a
        mux nobody reads from never pays the per-drain resolve).

        This is also the latency plane's VISIBILITY watermark: the first
        read after a commit is the moment a client could actually observe
        the committed round, so it finalizes every pending stage record."""
        sess = self._require(session_id)
        self.session.prefetch_digest = True
        out = self.session.read_patches(sess.doc_index)
        if self.latency_plane.enabled:
            self.latency_plane.mark_visible(self.clock())
        return out

    def read(self, session_id: int):
        """The session doc's resolved ``FormatSpan`` list.  Arms the fused
        digest prefetch like :meth:`patches` (the pump→read pattern is
        proven) and marks the latency plane's visibility watermark the
        same way."""
        sess = self._require(session_id)
        self.session.prefetch_digest = True
        out = self.session.read(sess.doc_index)
        if self.latency_plane.enabled:
            self.latency_plane.mark_visible(self.clock())
        return out

    def _require(self, session_id: int) -> ClientSession:
        sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(f"unknown serve session {session_id}")
        return sess

    def _fault_context(self, fields: Dict) -> Optional[List[Dict]]:
        """Flight-recorder context provider: a quarantine/rollback fault
        names its ``doc``; answer with the owning session(s)' recent
        admission-verdict tail so the dump shows the backpressure picture
        around the incident."""
        doc = fields.get("doc")
        if doc is None:
            return None
        out: List[Dict] = []
        for sid, sess in self._sessions.items():
            if sess.doc_index != doc:
                continue
            for rec in self.admission.verdict_tail(sid):
                # the verdict's own ``kind`` rides as ``verdict``: the
                # recorder's context envelope owns the ``kind`` key
                body = {k: v for k, v in rec.items() if k != "kind"}
                out.append({"doc": doc, "session": sid,
                            "verdict": rec.get("kind"), **body})
        return out or None

    # -- health ---------------------------------------------------------------

    def load_report(self) -> Dict:
        """This host's load along the router's placement dimensions
        (``FleetRouter.observe`` keyword-compatible): device slot load of
        on-device docs, host-bound (scalar-replay) load of fallback docs,
        and — on a paged session — the pool page load.  Rides inside
        :meth:`snapshot` so the fleet frontend ingests it through the SAME
        ``/serve.json`` surface an operator scrapes."""
        sizes = self.session._reshard_sizes()
        slot_load = 0
        host_bound = 0
        for d in range(self.doc_base, self.doc_base + self._next_doc):
            size = int(sizes[d]) if d < len(sizes) else 0
            if self.session.docs[d].fallback:
                host_bound += size
            else:
                slot_load += size
        report = {
            "slot_load": slot_load,
            "host_bound_load": host_bound,
            "docs": self._next_doc,
        }
        pool = getattr(self.session, "store", None)
        if pool is not None:
            report["page_load"] = int(pool.pool_stats()["pages_in_use"])
        return report

    @property
    def overloaded(self) -> bool:
        """Sustained-overload flag: backpressure currently engaged, or the
        open buffer alone can't drain (queue at max)."""
        return self.admission.backpressure

    def fusion_snapshot(self) -> Dict:
        """The ``/serve.json`` ``fusion`` section: how many tenants share
        this mux's device dispatches.  Standalone, the identity report —
        one tenant, one lane, one dispatch per committed round.  On a
        fused group member, the group's shared-window stats (injected via
        ``_fusion_stats``), so EVERY tenant's scrape shows the
        amortization it actually got."""
        if self._fusion_stats is not None:
            return self._fusion_stats()
        return {
            "grouped": False,
            "tenants": 1,
            "lanes": 1,
            "windows": self.rounds,
            "dispatches": self.rounds,
            "docs_per_dispatch": float(self._next_doc),
            "window_occupancy": round(
                self._next_doc / self.doc_capacity, 4
            ) if self.doc_capacity else 0.0,
        }

    def snapshot(self) -> Dict:
        """The ``/serve.json`` body (golden-shape test pins these keys):
        session table, bounded-queue state + typed verdict accounting,
        autotuned window state, and the round/apply tallies."""
        open_sessions = [s for s in self._sessions.values() if not s.closed]
        snap = {
            "host": self.host,
            # the backing session's storage layout — a fleet scrape must be
            # able to tell paged/ragged serving hosts (page-pool gauges
            # live; ragged adds the peritext_ragged_* walk gauges) from
            # padded ones without a second endpoint.  On "ragged" the mux's
            # staged drains route through the same prep/stage/dispatch trio
            # but every round is the ONE pool-wide ragged program — a
            # serving host never compiles a bucket ladder.
            "layout": getattr(self.session, "layout", "padded"),
            # whether serving rounds commit through the fused
            # device-resident pipeline (donated multi-round programs +
            # drain-end digest prefetch) — False only on compat sessions
            "fused_pipeline": bool(
                getattr(self.session, "fused_pipeline", False)
            ),
            "sessions": len(open_sessions),
            "sessions_total": len(self._sessions),
            "docs": self._next_doc,
            "doc_capacity": self.doc_capacity,
            "degraded_docs": self.degraded_docs,
            "fusion": self.fusion_snapshot(),
            "rounds": self.rounds,
            "applied_frames": self.applied,
            "buffered_frames": len(self._buffer),
            "overloaded": self.overloaded,
            "recent_sheds": max(
                0, self.admission.stats.shed - self._shed_mark
            ),
            "load": self.load_report(),
            "queue": self.admission.snapshot(),
            "window": self.tuner.snapshot(),
            "session_table": {
                str(sid): s.to_json()
                for sid, s in sorted(self._sessions.items())
            },
        }
        pool = getattr(self.session, "store", None)
        if pool is not None:
            snap["page_pool"] = pool.pool_stats()
        if self.auth is not None:
            snap["auth"] = {
                **self.auth.snapshot(),
                "per_frame": self.auth_per_frame,
            }
        return snap
