"""peritext-tpu: a TPU-native collaborative rich-text CRDT framework.

A brand-new implementation of the capabilities of Peritext (Ink & Switch's
rich-text CRDT; reference mounted at /root/reference) re-designed for TPU:

* :mod:`peritext_tpu.core` — scalar document oracle (the specification layer):
  full Micromerge semantics, changes/clocks, mark spans, patches, cursors.
* :mod:`peritext_tpu.ops` — packed document state and batched JAX/XLA kernels
  that apply (doc x op) tensors of CRDT operations across thousands of
  documents at once.
* :mod:`peritext_tpu.parallel` — replication: pubsub, change queues, vector
  clock anti-entropy, causal scheduling, and device-mesh sharding of the doc
  axis via jax.sharding.
* :mod:`peritext_tpu.api` — user-facing facades: single Doc, DocBatch (the TPU
  backend behind the InputOperation/Patch boundary), and the editor bridge.
* :mod:`peritext_tpu.store` — paged document storage: a device-resident
  global pool of fixed-size op pages + per-doc page tables behind
  ``layout="paged"`` on DocBatch/StreamingMerge (the padded layout stays
  the byte-equality oracle).
* :mod:`peritext_tpu.testing` — fuzz harness, trace replay, patch-accumulation
  oracle.
"""

from .core import (
    Change,
    CausalityError,
    Doc,
    Micromerge,
    Operation,
    PeritextError,
    span,
)
from .schema import ALL_MARKS, MARK_SPEC, MarkSchema, is_mark_type

__version__ = "0.1.0"

__all__ = [
    "Doc",
    "Micromerge",
    "Change",
    "Operation",
    "span",
    "PeritextError",
    "CausalityError",
    "MARK_SPEC",
    "MarkSchema",
    "ALL_MARKS",
    "is_mark_type",
    "__version__",
]
