"""The editor ↔ CRDT bridge (reference ``src/bridge.ts``).

Bidirectional transform at the framework's stable public boundary:

* **down** (local edit): an editor :class:`~.model.Transaction` becomes
  ``InputOperation`` dicts (``transaction_to_input_ops``, reference
  ``applyProsemirrorTransactionToMicromergeDoc`` ``src/bridge.ts:417-531``),
  is applied via ``Doc.change``, and the resulting patches are re-applied to
  the editor view — the view is *always* driven by patches, never by the
  original transaction, so the incremental path is exercised on every edit.
* **up** (remote change): ``Doc.apply_change`` patches become editor steps
  (``patch_to_steps``, reference
  ``extendProsemirrorTransactionWithMicromergePatch`` ``src/bridge.ts:138-199``).

Editor positions are 1-based (paragraph-open token at 0); all ±1 shifting
happens here and only here (reference ``src/bridge.ts:360-371``).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..core.doc import CONTENT_KEY, Doc
from ..core.errors import CausalityError
from ..core.types import Change, InputOperation, Patch
from ..parallel.change_queue import ChangeQueue
from ..parallel.pubsub import Publisher
from .model import (
    AddMarkStep,
    EditorDoc,
    RemoveMarkStep,
    ReplaceStep,
    ResetStep,
    Step,
    Transaction,
)

#: Default seed text, as in the reference demo (src/bridge.ts:118).
DEFAULT_INITIAL_TEXT = "Welcome to the Peritext editor!"


def content_index_from_pos(pos: int) -> int:
    """Editor position → CRDT content index (reference src/bridge.ts:360-371)."""
    return pos - 1


def pos_from_content_index(index: int) -> int:
    return index + 1


# ---------------------------------------------------------------------------
# down: editor transaction → input operations
# ---------------------------------------------------------------------------


def transaction_to_input_ops(txn: Transaction) -> List[InputOperation]:
    """Convert editor steps to index-based CRDT input operations.

    ``ReplaceStep`` with content becomes delete-then-insert, exactly as the
    reference translates a content-bearing ``ReplaceStep``
    (src/bridge.ts:428-453).
    """
    ops: List[InputOperation] = []
    for step in txn.steps:
        if isinstance(step, ReplaceStep):
            start = content_index_from_pos(step.from_pos)
            count = step.to_pos - step.from_pos
            if count > 0:
                ops.append(
                    {"path": [CONTENT_KEY], "action": "delete", "index": start, "count": count}
                )
            if step.text:
                ops.append(
                    {
                        "path": [CONTENT_KEY],
                        "action": "insert",
                        "index": start,
                        "values": list(step.text),
                    }
                )
        elif isinstance(step, (AddMarkStep, RemoveMarkStep)):
            action = "addMark" if isinstance(step, AddMarkStep) else "removeMark"
            op: InputOperation = {
                "path": [CONTENT_KEY],
                "action": action,
                "startIndex": content_index_from_pos(step.from_pos),
                "endIndex": content_index_from_pos(step.to_pos),
                "markType": step.mark_type,
            }
            if step.attrs is not None:
                op["attrs"] = dict(step.attrs)
            ops.append(op)
        elif isinstance(step, ResetStep):
            raise ValueError("ResetStep is patch-driven only; editors cannot emit it")
        else:
            raise TypeError(f"Unknown step type: {step!r}")
    return ops


def apply_transaction_to_doc(doc: Doc, txn: Transaction):
    """Editor transaction → (broadcastable Change, local patches)."""
    return doc.change(transaction_to_input_ops(txn))


# ---------------------------------------------------------------------------
# up: CRDT patch → editor steps
# ---------------------------------------------------------------------------


def patch_to_steps(patch: Patch) -> List[Step]:
    """Convert one CRDT patch to editor steps (reference src/bridge.ts:138-199)."""
    action = patch["action"]
    if action == "insert":
        pos = pos_from_content_index(patch["index"])
        return [
            ReplaceStep(pos, pos, "".join(patch["values"]), marks=patch.get("marks") or {})
        ]
    if action == "delete":
        pos = pos_from_content_index(patch["index"])
        return [ReplaceStep(pos, pos + patch["count"], "")]
    if action == "addMark":
        return [
            AddMarkStep(
                pos_from_content_index(patch["startIndex"]),
                pos_from_content_index(patch["endIndex"]),
                patch["markType"],
                patch.get("attrs"),
            )
        ]
    if action == "removeMark":
        return [
            RemoveMarkStep(
                pos_from_content_index(patch["startIndex"]),
                pos_from_content_index(patch["endIndex"]),
                patch["markType"],
                patch.get("attrs"),
            )
        ]
    if action == "makeList":
        return [ResetStep()]
    raise ValueError(f"Unsupported patch for editor: {action}")


def editor_doc_from_crdt(doc: Doc) -> EditorDoc:
    """Full render of the CRDT into an editor doc (reference
    ``prosemirrorDocFromCRDT``, src/bridge.ts:394-414)."""
    view = EditorDoc()
    for span in doc.get_text_with_formatting([CONTENT_KEY]):
        view.insert_at(len(view), span["text"], span["marks"])
    return view


# ---------------------------------------------------------------------------
# Editor: the headless analog of the reference's createEditor wiring
# ---------------------------------------------------------------------------


@dataclass
class EditorEvent:
    """One structured log entry (replaces the reference's DOM debug log,
    ``outputDebugForChange`` src/bridge.ts:235-242)."""

    kind: str  # "local-change" | "remote-change" | "flush"
    actor: str
    detail: Dict[str, Any] = field(default_factory=dict)


class Editor:
    """A headless collaborative editor replica.

    Wires together a CRDT replica, an incremental editor view, an outbound
    :class:`ChangeQueue`, and a :class:`Publisher` subscription — the same
    plumbing ``createEditor`` assembles (reference src/bridge.ts:204-347),
    minus the DOM.  Remote changes tolerate out-of-order delivery with a
    hold-back buffer (the reference gets this from causal queues plus
    ``applyChange``'s dep check).
    """

    def __init__(
        self,
        actor_id: str,
        publisher: Optional[Publisher] = None,
        queue_interval: float = 0.01,
        start_queue: bool = False,
        on_remote_patch: Optional[Callable[["Editor", Patch], None]] = None,
        on_event: Optional[Callable[[EditorEvent], None]] = None,
        backend: str = "scalar",
        actors: Optional[Sequence[str]] = None,
        backend_config: Optional[Dict[str, Any]] = None,
    ) -> None:
        """``backend`` selects who maintains the editor view:

        * ``"scalar"`` (default): the reference architecture — patches from
          the in-process scalar CRDT drive the view.
        * ``"tpu"``: the batched device engine — every change (local and
          remote) is also ingested into a :class:`~..parallel.streaming.
          StreamingMerge` session and the view is driven by its incremental
          ``read_patches`` stream.  Same ``InputOperation`` in, same
          ``Patch`` vocabulary out (the BASELINE boundary contract); the
          scalar ``Doc`` remains the local op *generator* (index→element
          anchoring needs full local state either way).  ``actors`` must
          declare the replica set (packed-id order is fixed up front).
        """
        self.actor_id = actor_id
        self.doc = Doc(actor_id)
        self.view = EditorDoc()
        self.publisher = publisher
        self.on_remote_patch = on_remote_patch
        self.on_event = on_event
        self.backend = backend
        self.session = None
        if backend == "tpu":
            from ..parallel.streaming import StreamingMerge

            config = dict(backend_config or {})
            config.setdefault("slot_capacity", 1024)
            config.setdefault("mark_capacity", 256)
            # generous round widths (cheap at num_docs=1): a single editor
            # transaction — e.g. a large paste — must fit one round, else
            # the session demotes the doc to scalar replay
            config.setdefault("round_insert_capacity", 512)
            config.setdefault("round_delete_capacity", 256)
            config.setdefault("round_mark_capacity", 128)
            self.session = StreamingMerge(
                num_docs=1, actors=list(actors or (actor_id,)), **config
            )
        elif backend != "scalar":
            raise ValueError(f"unknown merge backend: {backend!r}")
        self._holdback: List[Change] = []
        self.queue = ChangeQueue(self._flush, interval=queue_interval)
        if publisher is not None:
            publisher.subscribe(actor_id, self._receive)
        if start_queue:
            self.queue.start()

    # -- local edits (reference dispatchTransaction, src/bridge.ts:309-347) --

    def dispatch(self, txn: Transaction) -> Change:
        return self.dispatch_input_ops(transaction_to_input_ops(txn))

    def dispatch_input_ops(self, input_ops: List[InputOperation]) -> Change:
        """Apply raw input operations locally (the playback interpreter drives
        editors this way, reference ``executeTraceEvent`` src/playback.ts:102-115)."""
        change, patches = self.doc.change(input_ops)
        if self.session is not None:
            self._backend_ingest(change)
            self._backend_view_sync(remote=False)
        else:
            for patch in patches:
                for step in patch_to_steps(patch):
                    step.apply(self.view)
        self.queue.enqueue(change)
        self._emit("local-change", ops=len(change.ops), seq=change.seq)
        return change

    # -- tpu backend plumbing ----------------------------------------------

    def _backend_ingest(self, change: Change) -> None:
        self.session.ingest(0, [change])

    def _backend_view_sync(self, remote: bool) -> None:
        """Advance the view by the device session's incremental patches."""
        self.session.drain()
        for patch in self.session.read_patches(0):
            for step in patch_to_steps(patch):
                step.apply(self.view)
            if remote and self.on_remote_patch is not None:
                self.on_remote_patch(self, patch)

    # -- outbound ----------------------------------------------------------

    def _flush(self, changes: List[Change]) -> None:
        if self.publisher is not None:
            self.publisher.publish(self.actor_id, list(changes))
        self._emit("flush", count=len(changes))

    def sync(self) -> None:
        """Manual flush (the demo Sync button, reference src/index.ts:122-126)."""
        self.queue.flush()

    def disconnect(self) -> None:
        """Stop outbound flushing (simulated partition; reference queue.drop)."""
        self.queue.drop()

    # -- inbound (reference subscribe loop, src/bridge.ts:244-285) ---------

    def _receive(self, changes: List[Change]) -> None:
        self._holdback.extend(changes)
        self._drain_holdback()

    def _drain_holdback(self) -> None:
        progressed = True
        applied_remote = False
        while progressed and self._holdback:
            progressed = False
            remaining: List[Change] = []
            for change in self._holdback:
                if change.seq <= self.doc.clock.get(change.actor, 0):
                    progressed = True  # duplicate: drop silently
                    continue
                try:
                    patches = self.doc.apply_change(change)
                except CausalityError:
                    remaining.append(change)
                    continue
                progressed = True
                if self.session is not None:
                    self._backend_ingest(change)
                    applied_remote = True
                else:
                    for patch in patches:
                        for step in patch_to_steps(patch):
                            step.apply(self.view)
                        if self.on_remote_patch is not None:
                            self.on_remote_patch(self, patch)
                self._emit("remote-change", actor=change.actor, seq=change.seq)
            self._holdback = remaining
        if applied_remote:
            self._backend_view_sync(remote=True)

    def apply_remote(self, *changes: Change) -> None:
        """Directly deliver remote changes (tests / transports without pubsub)."""
        self._receive(list(changes))

    # -- misc --------------------------------------------------------------

    def _emit(self, kind: str, **detail) -> None:
        if self.on_event is not None:
            self.on_event(EditorEvent(kind, self.actor_id, detail))

    def rerender(self) -> None:
        """Full re-render of the view (used after init).  Scalar backend:
        from the CRDT; tpu backend: advance by the session's patch stream
        (the view is exclusively patch-driven there)."""
        if self.session is not None:
            self._backend_view_sync(remote=False)
        else:
            self.view = editor_doc_from_crdt(self.doc)

    @property
    def text(self) -> str:
        return self.view.text


def create_editor(
    actor_id: str,
    publisher: Publisher,
    queue_interval: float = 0.01,
    start_queue: bool = False,
    **kwargs,
) -> Editor:
    """Factory mirroring the reference's ``createEditor`` (src/bridge.ts:204)."""
    return Editor(
        actor_id,
        publisher,
        queue_interval=queue_interval,
        start_queue=start_queue,
        **kwargs,
    )


def initialize_docs(editors: Sequence[Editor], initial_text: str = DEFAULT_INITIAL_TEXT) -> Change:
    """Seed every editor with shared history via ONE origin change from the
    first editor (reference ``initializeDocs``, src/bridge.ts:117-126) —
    concurrent edits then share the origin's element ids."""
    first, rest = editors[0], editors[1:]
    change, _ = first.doc.change(
        [
            {"path": [], "action": "makeList", "key": CONTENT_KEY},
            {
                "path": [CONTENT_KEY],
                "action": "insert",
                "index": 0,
                "values": list(initial_text),
            },
        ]
    )
    for editor in rest:
        editor.doc.apply_change(change)
    for editor in editors:
        if editor.session is not None:  # tpu backend: the view is session-fed
            editor._backend_ingest(change)
        editor.rerender()
    return change


def new_comment_id() -> str:
    """Fresh comment id (reference uses uuid, src/bridge.ts:66)."""
    return str(uuid.uuid4())
