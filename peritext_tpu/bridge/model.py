"""Headless rich-text editor document model.

The reference integrates with ProseMirror: its editor document is
``doc(paragraph(text))`` and edits arrive as ProseMirror steps
(``ReplaceStep`` / ``AddMarkStep`` / ``RemoveMarkStep``, reference
``src/bridge.ts:424-528``).  This framework is headless, so this module
supplies the equivalent editor-side document model and step algebra that the
bridge translates to and from CRDT input operations and patches.

Position convention (kept deliberately identical to the reference): editor
positions are **1-based** — position 0 is the paragraph-open token, so editor
position ``p`` addresses the character at CRDT index ``p - 1``
(``contentPosFromProsemirrorPos``, reference ``src/bridge.ts:360-371``).  The
bridge is the only place the ±1 shift happens.

Mark application follows ProseMirror ``Mark.addToSet`` semantics as the
reference relies on them: non-``allow_multiple`` marks replace an existing
mark of the same type; ``allow_multiple`` marks (comments) form a set keyed by
their ``id`` attr.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

from ..core.spans import add_characters_to_spans
from ..core.types import FormatSpan, MarkMap
from ..schema import MARK_SPEC, excludes_of


def _add_mark_to_map(marks: MarkMap, mark_type: str, attrs: Optional[Dict[str, Any]]) -> MarkMap:
    out = dict(marks)
    # PM Mark.addToSet consults the schema's excludes (presentation half of
    # the reference markSpec), in BOTH directions: an existing mark that
    # excludes the new type rejects the add outright, and the new mark
    # evicts the types it excludes.  The default excludes only the mark's
    # own type (same-type replace below); comments exclude nothing.
    for existing in out:
        if existing != mark_type and mark_type in excludes_of(existing):
            return out
    for excluded in excludes_of(mark_type):
        if excluded != mark_type:
            out.pop(excluded, None)
    spec = MARK_SPEC.get(mark_type)
    if spec is not None and spec.allow_multiple:
        entries = [dict(e) for e in out.get(mark_type, [])]
        entry = dict(attrs or {})
        if not any(e.get("id") == entry.get("id") for e in entries):
            entries.append(entry)
        out[mark_type] = sorted(entries, key=lambda e: str(e.get("id")))
    elif mark_type == "link":
        out[mark_type] = {"active": True, "url": (attrs or {}).get("url")}
    else:
        out[mark_type] = {"active": True}
    return out


def _remove_mark_from_map(
    marks: MarkMap, mark_type: str, attrs: Optional[Dict[str, Any]]
) -> MarkMap:
    out = dict(marks)
    spec = MARK_SPEC.get(mark_type)
    if spec is not None and spec.allow_multiple:
        wanted_id = (attrs or {}).get("id")
        entries = [e for e in out.get(mark_type, []) if wanted_id is not None and e.get("id") != wanted_id]
        if entries:
            out[mark_type] = entries
        else:
            out.pop(mark_type, None)
    else:
        out.pop(mark_type, None)
    return out


class EditorDoc:
    """The editor's view of one document: a single paragraph of marked text.

    Stored as parallel per-character arrays (char + mark map), which is the
    natural incremental-patch target; :meth:`spans` flattens to the same
    ``FormatSpan`` shape the CRDT read path produces, so tests can assert the
    incremental view equals the full CRDT render byte for byte.
    """

    def __init__(self, chars: Optional[List[str]] = None, marks: Optional[List[MarkMap]] = None):
        self.chars: List[str] = list(chars or [])
        self.marks: List[MarkMap] = [dict(m) for m in (marks or [])]
        assert len(self.chars) == len(self.marks)

    # -- queries -----------------------------------------------------------

    @property
    def text(self) -> str:
        return "".join(self.chars)

    def __len__(self) -> int:
        return len(self.chars)

    @property
    def size(self) -> int:
        """Editor-coordinate size: content length + the 2 paragraph tokens."""
        return len(self.chars) + 2

    def spans(self) -> List[FormatSpan]:
        out: List[FormatSpan] = []
        for ch, m in zip(self.chars, self.marks):
            add_characters_to_spans([ch], m, out)
        return out

    def copy(self) -> "EditorDoc":
        return EditorDoc(self.chars, self.marks)

    # -- content-index mutations (0-based; the bridge handles the ±1) ------

    def insert_at(self, index: int, text: str, marks: Optional[MarkMap] = None) -> None:
        if not 0 <= index <= len(self.chars):
            raise IndexError(f"insert index {index} out of bounds 0..{len(self.chars)}")
        mm = dict(marks or {})
        self.chars[index:index] = list(text)
        self.marks[index:index] = [dict(mm) for _ in text]

    def delete_at(self, index: int, count: int) -> None:
        if count < 0 or not 0 <= index <= len(self.chars) - count:
            raise IndexError(f"delete [{index}, {index + count}) out of bounds")
        del self.chars[index : index + count]
        del self.marks[index : index + count]

    def add_mark_at(
        self, start: int, end: int, mark_type: str, attrs: Optional[Dict[str, Any]] = None
    ) -> None:
        for i in range(max(start, 0), min(end, len(self.chars))):
            self.marks[i] = _add_mark_to_map(self.marks[i], mark_type, attrs)

    def remove_mark_at(
        self, start: int, end: int, mark_type: str, attrs: Optional[Dict[str, Any]] = None
    ) -> None:
        for i in range(max(start, 0), min(end, len(self.chars))):
            self.marks[i] = _remove_mark_from_map(self.marks[i], mark_type, attrs)

    def reset(self) -> None:
        self.chars, self.marks = [], []

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, EditorDoc)
            and self.chars == other.chars
            and self.marks == other.marks
        )

    def __repr__(self) -> str:
        return f"EditorDoc({self.text!r})"


# ---------------------------------------------------------------------------
# Steps (the editor-side analogs of the three ProseMirror step types the
# reference translates, src/bridge.ts:424-528) and transactions.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ReplaceStep:
    """Replace editor range [from_pos, to_pos) with ``text``.

    Insert = zero-width range; delete = empty text; replace = both (the
    reference translates that as delete-then-insert, src/bridge.ts:428-444).
    """

    from_pos: int
    to_pos: int
    text: str = ""
    marks: Optional[MarkMap] = None

    def apply(self, doc: EditorDoc) -> None:
        index = self.from_pos - 1
        doc.delete_at(index, self.to_pos - self.from_pos)
        if self.text:
            doc.insert_at(index, self.text, self.marks)


@dataclass(frozen=True)
class AddMarkStep:
    from_pos: int
    to_pos: int
    mark_type: str
    attrs: Optional[Dict[str, Any]] = None

    def apply(self, doc: EditorDoc) -> None:
        doc.add_mark_at(self.from_pos - 1, self.to_pos - 1, self.mark_type, self.attrs)


@dataclass(frozen=True)
class RemoveMarkStep:
    from_pos: int
    to_pos: int
    mark_type: str
    attrs: Optional[Dict[str, Any]] = None

    def apply(self, doc: EditorDoc) -> None:
        doc.remove_mark_at(self.from_pos - 1, self.to_pos - 1, self.mark_type, self.attrs)


@dataclass(frozen=True)
class ResetStep:
    """Clear the document (the editor-side effect of a ``makeList`` patch —
    the reference re-renders the whole doc in that case)."""

    def apply(self, doc: EditorDoc) -> None:
        doc.reset()


Step = Union[ReplaceStep, AddMarkStep, RemoveMarkStep, ResetStep]


@dataclass
class Transaction:
    """An ordered batch of steps (the editor-side unit the bridge converts)."""

    steps: List[Step] = field(default_factory=list)

    # builder helpers (mirroring the PM Transaction API shape)
    def replace(self, from_pos: int, to_pos: int, text: str = "", marks: Optional[MarkMap] = None) -> "Transaction":
        self.steps.append(ReplaceStep(from_pos, to_pos, text, marks))
        return self

    def insert_text(self, pos: int, text: str, marks: Optional[MarkMap] = None) -> "Transaction":
        return self.replace(pos, pos, text, marks)

    def delete(self, from_pos: int, to_pos: int) -> "Transaction":
        return self.replace(from_pos, to_pos, "")

    def add_mark(self, from_pos: int, to_pos: int, mark_type: str, attrs=None) -> "Transaction":
        self.steps.append(AddMarkStep(from_pos, to_pos, mark_type, attrs))
        return self

    def remove_mark(self, from_pos: int, to_pos: int, mark_type: str, attrs=None) -> "Transaction":
        self.steps.append(RemoveMarkStep(from_pos, to_pos, mark_type, attrs))
        return self

    def apply_to(self, doc: EditorDoc) -> EditorDoc:
        for step in self.steps:
            step.apply(doc)
        return doc
