"""Editor commands — the headless analog of the reference keymap.

The reference binds Mod-b / Mod-i / Mod-e / Mod-k to mark toggles
(``src/bridge.ts:60-74``): bold and italic toggle, Mod-e adds a comment with a
fresh uuid, Mod-k wraps the selection in a link.  Here those are plain
functions over an :class:`~.bridge.Editor` and a selection given as editor
positions (1-based, like the reference's ProseMirror selections).
"""

from __future__ import annotations

from typing import Optional

from ..core.types import Change
from .bridge import Editor, content_index_from_pos, new_comment_id
from .model import Transaction


def _range_has_mark(editor: Editor, from_pos: int, to_pos: int, mark_type: str) -> bool:
    start, end = content_index_from_pos(from_pos), content_index_from_pos(to_pos)
    chars = editor.view.marks[start:end]
    return bool(chars) and all(mark_type in m for m in chars)


def toggle_mark(editor: Editor, from_pos: int, to_pos: int, mark_type: str) -> Change:
    """ProseMirror-style toggle: remove if the whole range is marked, else add."""
    txn = Transaction()
    if _range_has_mark(editor, from_pos, to_pos, mark_type):
        txn.remove_mark(from_pos, to_pos, mark_type)
    else:
        txn.add_mark(from_pos, to_pos, mark_type)
    return editor.dispatch(txn)


def toggle_bold(editor: Editor, from_pos: int, to_pos: int) -> Change:
    """Mod-b (reference src/bridge.ts:61)."""
    return toggle_mark(editor, from_pos, to_pos, "strong")


def toggle_italic(editor: Editor, from_pos: int, to_pos: int) -> Change:
    """Mod-i (reference src/bridge.ts:62)."""
    return toggle_mark(editor, from_pos, to_pos, "em")


def add_comment(
    editor: Editor, from_pos: int, to_pos: int, comment_id: Optional[str] = None
) -> Change:
    """Mod-e: comment on the selection with a fresh id (src/bridge.ts:63-67)."""
    cid = comment_id if comment_id is not None else new_comment_id()
    return editor.dispatch(
        Transaction().add_mark(from_pos, to_pos, "comment", {"id": cid})
    )


def set_link(editor: Editor, from_pos: int, to_pos: int, url: str) -> Change:
    """Mod-k: link the selection to ``url`` (src/bridge.ts:68-73)."""
    return editor.dispatch(
        Transaction().add_mark(from_pos, to_pos, "link", {"url": url})
    )


def type_text(editor: Editor, pos: int, text: str) -> Change:
    """Insert ``text`` at an editor position (plain keystroke input)."""
    return editor.dispatch(Transaction().insert_text(pos, text))


def delete_range(editor: Editor, from_pos: int, to_pos: int) -> Change:
    return editor.dispatch(Transaction().delete(from_pos, to_pos))
