"""Trace playback: scripted multi-editor sessions (reference ``src/playback.ts``).

A *trace* is a flat list of events, each either an ``InputOperation`` tagged
with the editor that performs it, a ``{"action": "sync"}`` barrier that
flushes every editor's outbound queue, or a ``{"action": "restart"}`` marker
(a no-op for the interpreter; demo loops use it to delimit iterations).
Events may carry a ``delay`` in milliseconds, honored only when playing in
realtime mode — tests and benchmarks play traces instantly.

``trace_from_spec`` converts a concurrent-edit ``TraceSpec`` (the shape the
ported reference test suite uses) into a trace that types the initial text,
applies each side's ops concurrently, and syncs at the end (reference
``testToTrace``, src/playback.ts:13-36).  ``simulate_typing_for_input_op``
expands a multi-character insert into per-keystroke events
(src/playback.ts:38-51).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional

from ..core.doc import CONTENT_KEY
from ..core.types import InputOperation
from .bridge import Editor

#: A trace event: an InputOperation + {"editorId": ...}, or {"action": "sync"},
#: or {"action": "restart"}; all optionally with {"delay": milliseconds}.
TraceEvent = Dict[str, Any]
Trace = List[TraceEvent]

#: Default inter-keystroke delay for simulated typing, in ms (reference :44).
TYPING_DELAY_MS = 50


def simulate_typing_for_input_op(editor_id: str, op: InputOperation) -> List[TraceEvent]:
    """Expand a multi-char insert into one event per keystroke; everything
    else passes through as a single event."""
    if op["action"] == "insert":
        return [
            {
                **op,
                "editorId": editor_id,
                "path": [CONTENT_KEY],
                "delay": TYPING_DELAY_MS,
                "values": [v],
                "index": op["index"] + i,
            }
            for i, v in enumerate(op["values"])
        ]
    return [{**op, "editorId": editor_id, "path": [CONTENT_KEY]}]


def trace_from_spec(trace_spec: Mapping[str, Any]) -> Trace:
    """Concurrent-edit spec → trace: seed text on alice, sync, both sides
    type their ops concurrently, final sync (reference src/playback.ts:13-36)."""
    initial_text = trace_spec.get("initialText")
    ops1, ops2 = trace_spec.get("inputOps1"), trace_spec.get("inputOps2")
    if not initial_text or ops1 is None or ops2 is None:
        raise ValueError("Expected full trace spec")

    trace: Trace = [
        {"editorId": "alice", "path": [], "action": "makeList", "key": CONTENT_KEY, "delay": 0},
        {"action": "sync", "delay": 0},
        {
            "editorId": "alice",
            "path": [CONTENT_KEY],
            "action": "insert",
            "index": 0,
            "values": list(initial_text),
        },
        {"action": "sync"},
    ]
    for op in ops1:
        trace.extend(simulate_typing_for_input_op("alice", op))
    for op in ops2:
        trace.extend(simulate_typing_for_input_op("bob", op))
    trace.append({"action": "sync"})
    return trace


def execute_trace_event(
    event: TraceEvent,
    editors: Mapping[str, Editor],
    on_sync: Optional[Callable[[], None]] = None,
    realtime: bool = False,
) -> None:
    """Interpret one trace event (reference ``executeTraceEvent``,
    src/playback.ts:82-121)."""
    action = event.get("action")
    if action == "sync":
        if on_sync is not None:
            on_sync()
        for editor in editors.values():
            editor.queue.flush()
    elif action == "restart":
        pass
    else:
        editor = editors.get(event.get("editorId", ""))
        if editor is None:
            raise KeyError("Encountered a trace event for a missing editor")
        op = {k: v for k, v in event.items() if k not in ("editorId", "delay")}
        editor.dispatch_input_ops([op])
    if realtime and event.get("delay"):
        time.sleep(event["delay"] / 1000.0)


def play_trace(
    trace: Iterable[TraceEvent],
    editors: Mapping[str, Editor],
    on_sync: Optional[Callable[[], None]] = None,
    realtime: bool = False,
) -> None:
    for event in trace:
        execute_trace_event(event, editors, on_sync=on_sync, realtime=realtime)


def endless_loop(trace: List[TraceEvent]) -> Iterator[TraceEvent]:
    """Cycle a trace forever (reference ``endlessLoop``, src/essay-demo.ts:92-98)."""
    while True:
        yield from trace
