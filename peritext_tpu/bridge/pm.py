"""ProseMirror wire-format interop for the editor bridge.

The reference's L2 is a live ProseMirror plugin (``src/bridge.ts:204-347``)
wired to a real browser view; its edits arrive as ``prosemirror-transform``
steps and its document is ``doc(paragraph(text))`` under the schema in
``src/schema.ts:45-96``.  This image has no node runtime and no network
egress, so a real PM bundle cannot be vendored or executed here — instead
this module speaks PM's exact JSON wire formats, and the conformance suite
(``tests/test_pm_conformance.py``) replays transaction fixtures authored in
the byte-level schema ``Step.toJSON()`` / ``Node.toJSON()`` produce, so a
real ProseMirror can drive the HTTP bridge unchanged the moment one is
available:

* step JSON <-> the bridge's step algebra (``bridge.model``):
  ``{"stepType": "replace", "from": f, "to": t, "slice": {...}}`` /
  ``addMark`` / ``removeMark`` exactly as ``prosemirror-transform`` emits
  them (ReplaceStep.toJSON / AddMarkStep.toJSON);
* document JSON <-> ``EditorDoc``: ``doc(paragraph(text...))`` with mark
  JSON per ``Mark.toJSON()`` ({"type": name} + "attrs" when the type has
  attrs);
* mark-set JSON <-> the bridge ``MarkMap`` (comments are ``allowMultiple``:
  one PM mark per comment id, reference src/schema.ts:79-92).

Positions: PM positions in a single-paragraph doc are exactly the bridge's
1-based convention (position 0 is the paragraph-open token,
``contentPosFromProsemirrorPos`` reference src/bridge.ts:360-371), so no
shifting happens here — the bridge remains the only place the ±1 shift
exists.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..core.types import MarkMap
from ..schema import MARK_SPEC
from .model import (
    AddMarkStep,
    EditorDoc,
    RemoveMarkStep,
    ReplaceStep,
    Step,
    Transaction,
)

#: mark types of the reference schema (src/schema.ts:45-96) and whether
#: their PM serialization carries an attrs object
_PM_MARK_ATTRS = {
    "strong": (),
    "em": (),
    "link": ("url",),
    "comment": ("id",),
}


class PMFormatError(ValueError):
    """Raised when JSON does not match ProseMirror's wire schema."""


# -- marks -------------------------------------------------------------------


def marks_to_pm(marks: Optional[MarkMap]) -> List[Dict[str, Any]]:
    """Bridge MarkMap -> PM mark-set JSON (``Mark.toJSON()`` list, sorted in
    schema rank order like PM's ``Mark.addToSet`` maintains)."""
    out: List[Dict[str, Any]] = []
    for mark_type in _PM_MARK_ATTRS:
        val = (marks or {}).get(mark_type)
        if val is None:
            continue
        spec = MARK_SPEC.get(mark_type)
        if spec is not None and spec.allow_multiple:
            for entry in val:  # one PM mark per comment id
                out.append({"type": mark_type, "attrs": dict(entry)})
        elif mark_type == "link":
            out.append({"type": "link", "attrs": {"url": val.get("url")}})
        else:
            out.append({"type": mark_type})
    return out


def marks_from_pm(pm_marks: Optional[List[Dict[str, Any]]]) -> MarkMap:
    """PM mark-set JSON -> bridge MarkMap."""
    marks: MarkMap = {}
    for m in pm_marks or []:
        if not isinstance(m, dict) or "type" not in m:
            raise PMFormatError(f"bad mark json: {m!r}")
        mark_type = m["type"]
        if mark_type not in _PM_MARK_ATTRS:
            raise PMFormatError(f"unknown mark type: {mark_type!r}")
        attrs = m.get("attrs") or {}
        spec = MARK_SPEC.get(mark_type)
        if spec is not None and spec.allow_multiple:
            entries = list(marks.get(mark_type, []))
            if not any(e.get("id") == attrs.get("id") for e in entries):
                entries.append(dict(attrs))
            marks[mark_type] = sorted(entries, key=lambda e: str(e.get("id")))
        elif mark_type == "link":
            marks["link"] = {"active": True, "url": attrs.get("url")}
        else:
            marks[mark_type] = {"active": True}
    return marks


def _mark_attrs_of(mark_type: str, marks: MarkMap):
    """attrs to put on an Add/RemoveMarkStep for ``mark_type`` in a map."""
    val = marks.get(mark_type)
    if mark_type == "link" and isinstance(val, dict):
        return {"url": val.get("url")}
    return None


# -- steps -------------------------------------------------------------------


def step_from_pm(step_json: Dict[str, Any]) -> Step:
    """``Step.toJSON()`` -> the bridge's step algebra.

    Replace slices are restricted to what the reference's own bridge
    accepts: text content inside one paragraph (src/bridge.ts:424-466 walks
    ``slice.content`` text nodes; block-structure changes are out of the
    flat-text CRDT's model)."""
    if not isinstance(step_json, dict):
        raise PMFormatError(f"step must be an object: {step_json!r}")
    kind = step_json.get("stepType")
    if kind == "replace":
        frm, to = _positions(step_json)
        slice_json = step_json.get("slice")
        text, marks = _slice_text(slice_json)
        return ReplaceStep(frm, to, text, marks)
    if kind in ("addMark", "removeMark"):
        frm, to = _positions(step_json)
        mark = step_json.get("mark")
        if not isinstance(mark, dict) or "type" not in mark:
            raise PMFormatError(f"bad mark in step: {step_json!r}")
        if mark["type"] not in _PM_MARK_ATTRS:
            raise PMFormatError(f"unknown mark type: {mark['type']!r}")
        cls = AddMarkStep if kind == "addMark" else RemoveMarkStep
        return cls(frm, to, mark["type"], mark.get("attrs"))
    raise PMFormatError(f"unsupported stepType: {kind!r}")


def step_to_pm(step: Step) -> Dict[str, Any]:
    """Bridge step -> ``Step.toJSON()`` schema (what a PM client would feed
    ``Step.fromJSON`` to apply remote patches)."""
    if isinstance(step, ReplaceStep):
        out: Dict[str, Any] = {
            "stepType": "replace", "from": step.from_pos, "to": step.to_pos,
        }
        if step.text:
            node: Dict[str, Any] = {"type": "text", "text": step.text}
            pm_marks = marks_to_pm(step.marks)
            if pm_marks:
                node["marks"] = pm_marks
            out["slice"] = {"content": [node]}
        return out
    if isinstance(step, (AddMarkStep, RemoveMarkStep)):
        mark: Dict[str, Any] = {"type": step.mark_type}
        if step.attrs:
            mark["attrs"] = dict(step.attrs)
        return {
            "stepType": "addMark" if isinstance(step, AddMarkStep) else "removeMark",
            "from": step.from_pos,
            "to": step.to_pos,
            "mark": mark,
        }
    raise PMFormatError(f"step {step!r} has no PM serialization")


def transaction_from_pm(steps_json: List[Dict[str, Any]]) -> Transaction:
    """A PM transaction's ``steps`` array -> bridge Transaction."""
    txn = Transaction()
    for s in steps_json:
        txn.steps.append(step_from_pm(s))
    return txn


def _positions(step_json: Dict[str, Any]):
    frm, to = step_json.get("from"), step_json.get("to")
    if not isinstance(frm, int) or not isinstance(to, int) or not 0 < frm <= to:
        raise PMFormatError(f"bad step positions: {step_json!r}")
    return frm, to


def _slice_text(slice_json):
    """Extract (text, marks) from a replace slice; None slice = deletion."""
    if slice_json is None:
        return "", None
    if not isinstance(slice_json, dict):
        raise PMFormatError(f"bad slice: {slice_json!r}")
    if slice_json.get("openStart") or slice_json.get("openEnd"):
        raise PMFormatError("open slices (block joins) are outside the flat-text model")
    text, marks = [], None
    for node in slice_json.get("content", []):
        if not isinstance(node, dict) or node.get("type") != "text":
            raise PMFormatError(f"non-text slice content: {node!r}")
        text.append(node.get("text", ""))
        node_marks = marks_from_pm(node.get("marks"))
        if marks is None:
            marks = node_marks
        elif marks != node_marks:
            # the reference's bridge applies one mark set per replace; PM
            # multi-mark-run slices arrive as separate keystrokes in practice
            raise PMFormatError("replace slice mixes mark sets")
    return "".join(text), marks


# -- documents ---------------------------------------------------------------


def editor_doc_to_pm(doc: EditorDoc) -> Dict[str, Any]:
    """EditorDoc -> ``Node.toJSON()`` of the reference schema:
    doc(paragraph(text runs grouped by identical mark sets))."""
    runs: List[Dict[str, Any]] = []
    for span in doc.spans():
        node: Dict[str, Any] = {"type": "text", "text": span["text"]}
        pm_marks = marks_to_pm(span.get("marks"))
        if pm_marks:
            node["marks"] = pm_marks
        if node["text"]:
            runs.append(node)
    paragraph: Dict[str, Any] = {"type": "paragraph"}
    if runs:
        paragraph["content"] = runs
    return {"type": "doc", "content": [paragraph]}


def editor_doc_from_pm(doc_json: Dict[str, Any]) -> EditorDoc:
    """``Node.toJSON()`` -> EditorDoc (single-paragraph docs, the reference
    schema's shape — src/schema.ts:50-57 content: "paragraph+" with the demo
    and CRDT both flat)."""
    if not isinstance(doc_json, dict) or doc_json.get("type") != "doc":
        raise PMFormatError(f"not a doc node: {doc_json!r}")
    paragraphs = doc_json.get("content", [])
    if len(paragraphs) != 1 or paragraphs[0].get("type") != "paragraph":
        raise PMFormatError("only single-paragraph docs map onto the flat-text CRDT")
    doc = EditorDoc()
    index = 0
    for node in paragraphs[0].get("content", []):
        if node.get("type") != "text":
            raise PMFormatError(f"non-text paragraph content: {node!r}")
        marks = marks_from_pm(node.get("marks"))
        doc.insert_at(index, node.get("text", ""), marks or None)
        index += len(node.get("text", ""))
    return doc
