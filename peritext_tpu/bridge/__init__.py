"""Editor bridge: headless editor model + CRDT transforms (reference
``src/bridge.ts``)."""

from .bridge import (
    DEFAULT_INITIAL_TEXT,
    Editor,
    EditorEvent,
    apply_transaction_to_doc,
    content_index_from_pos,
    create_editor,
    editor_doc_from_crdt,
    initialize_docs,
    new_comment_id,
    patch_to_steps,
    pos_from_content_index,
    transaction_to_input_ops,
)
from .model import (
    AddMarkStep,
    EditorDoc,
    RemoveMarkStep,
    ReplaceStep,
    ResetStep,
    Step,
    Transaction,
)

__all__ = [
    "DEFAULT_INITIAL_TEXT",
    "AddMarkStep",
    "Editor",
    "EditorDoc",
    "EditorEvent",
    "RemoveMarkStep",
    "ReplaceStep",
    "ResetStep",
    "Step",
    "Transaction",
    "apply_transaction_to_doc",
    "content_index_from_pos",
    "create_editor",
    "editor_doc_from_crdt",
    "initialize_docs",
    "new_comment_id",
    "patch_to_steps",
    "pos_from_content_index",
    "transaction_to_input_ops",
]
