"""Packed device-side document state.

The scalar oracle's per-element metadata list (core/doc.py ListItemMeta;
reference ``ListItemMetadata`` src/micromerge.ts:341-357) becomes a
struct-of-arrays over a padded ``(D docs x S slots)`` tensor, and the
reference's per-gap mark-op *sets* become a grow-only ``(D x M)`` mark-op
table.  The gap sets are an incremental cache; the convergent semantics is a
pure function of (element order, mark table) — an op covers a character iff
its boundary anchors straddle that character's gap in the final element order
— so the device path stores only the table and resolves spans at read time
(see ops/resolve.py).  That formulation is order-independent, which is what
makes it batchable *and* removes the reference's materialized-gap divergence
bugs (its traces/ record them).

All identifiers are interned to int32 host-side (see ops/encode.py):
op IDs become (counter, actor_index) pairs compared lexicographically, where
actor indices are assigned in sorted-actor-string order so device ordering
matches the reference's string comparison (src/micromerge.ts:1389-1403).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# Boundary-kind encoding (core/types.py Boundary kinds)
BK_BEFORE = 0
BK_AFTER = 1
BK_START_OF_TEXT = 2
BK_END_OF_TEXT = 3

# Mark action encoding
MA_ADD = 1
MA_REMOVE = 2


class PackedDocs(NamedTuple):
    """Batched document state; leading axis D is the (shardable) doc axis.

    Slots [0, num_slots[d]) of doc d hold its elements in document order,
    tombstones included.  Element IDs are (ctr, actor) int32 pairs; actor 0 is
    reserved/invalid.
    """

    # element axis (D, S)
    elem_ctr: jnp.ndarray  # int32
    elem_actor: jnp.ndarray  # int32
    char: jnp.ndarray  # int32 codepoint
    deleted: jnp.ndarray  # bool
    # mark-op table (D, M)
    m_action: jnp.ndarray  # int32: MA_ADD / MA_REMOVE (0 = empty row)
    m_type: jnp.ndarray  # int32: schema.MARK_INDEX
    m_start_kind: jnp.ndarray  # int32 BK_*
    m_start_ctr: jnp.ndarray  # int32
    m_start_actor: jnp.ndarray  # int32
    m_end_kind: jnp.ndarray  # int32
    m_end_ctr: jnp.ndarray  # int32
    m_end_actor: jnp.ndarray  # int32
    m_op_ctr: jnp.ndarray  # int32
    m_op_actor: jnp.ndarray  # int32
    m_attr: jnp.ndarray  # int32 interned attr (url/comment id); 0 = none
    # scalars per doc (D,)
    num_slots: jnp.ndarray  # int32
    num_marks: jnp.ndarray  # int32
    overflow: jnp.ndarray  # bool: any capacity exceeded (slot or mark table)

    @property
    def num_docs(self) -> int:
        return self.elem_ctr.shape[0]

    @property
    def slot_capacity(self) -> int:
        return self.elem_ctr.shape[1]

    @property
    def mark_capacity(self) -> int:
        return self.m_action.shape[1]


def empty_docs(num_docs: int, slot_capacity: int, mark_capacity: int) -> PackedDocs:
    """Fresh empty batch (documents are built by applying their change logs)."""
    d, s, m = num_docs, slot_capacity, mark_capacity
    zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
    return PackedDocs(
        elem_ctr=zi(d, s),
        elem_actor=zi(d, s),
        char=zi(d, s),
        deleted=jnp.zeros((d, s), bool),
        m_action=zi(d, m),
        m_type=zi(d, m),
        m_start_kind=zi(d, m),
        m_start_ctr=zi(d, m),
        m_start_actor=zi(d, m),
        m_end_kind=zi(d, m),
        m_end_ctr=zi(d, m),
        m_end_actor=zi(d, m),
        m_op_ctr=zi(d, m),
        m_op_actor=zi(d, m),
        m_attr=zi(d, m),
        num_slots=zi(d),
        num_marks=zi(d),
        overflow=jnp.zeros((d,), bool),
    )


def to_numpy(state: PackedDocs) -> "PackedDocs":
    return PackedDocs(*(np.asarray(x) for x in state))
