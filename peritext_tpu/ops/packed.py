"""Packed device-side document state.

The scalar oracle's per-element metadata list (core/doc.py ListItemMeta;
reference ``ListItemMetadata`` src/micromerge.ts:341-357) becomes a
struct-of-arrays over a padded ``(D docs x S slots)`` tensor, and the
reference's per-gap mark-op *sets* become a grow-only ``(D x M)`` mark-op
table.  The gap sets are an incremental cache; the convergent semantics is a
pure function of (element order, mark table) — an op covers a character iff
its boundary anchors straddle that character's gap in the final element order
— so the device path stores only the table and resolves spans at read time
(see ops/resolve.py).  That formulation is order-independent, which is what
makes it batchable *and* removes the reference's materialized-gap divergence
bugs (its traces/ record them).

Element and op identifiers are single int32s: ``(counter << ACTOR_BITS) |
actor_index`` with actor indices assigned in sorted-actor-string order
(ops/encode.py), so plain integer comparison IS the reference's op-ID order
(counter first, then lexicographic actor; src/micromerge.ts:1389-1403).
Halving the bytes per identifier matters: the sequential insert loop is HBM
bandwidth bound, and it carries exactly two (D, S) arrays — packed element
ids and characters.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# Identifier packing: id = (ctr << ACTOR_BITS) | actor_index.
# actor 0 is reserved; packed id 0 means HEAD / empty slot.
ACTOR_BITS = 10
MAX_ACTORS = (1 << ACTOR_BITS) - 1  # 1023 actors per document
MAX_CTR = (1 << (31 - ACTOR_BITS)) - 1  # ~2M ops per document


def pack_id(ctr: int, actor_index: int) -> int:
    return (ctr << ACTOR_BITS) | actor_index


def unpack_id(packed: int):
    return packed >> ACTOR_BITS, packed & MAX_ACTORS


# Boundary-kind encoding (core/types.py Boundary kinds)
BK_BEFORE = 0
BK_AFTER = 1
BK_START_OF_TEXT = 2
BK_END_OF_TEXT = 3

# Mark action encoding
MA_ADD = 1
MA_REMOVE = 2

# Map-register value kinds (device LWW registers for map objects; the scalar
# semantics is core/doc.py:_apply_op's map branch, reference
# src/micromerge.ts:1151-1175).  A register row (r_op != 0) is the current
# LWW winner for one (object, key) pair.
VK_DELETED = 0  # winning op was a del: key absent
VK_STR = 1  # r_val = interned string id
VK_INT = 2  # r_val = the value (int32 range)
VK_TRUE = 3
VK_FALSE = 4
VK_NULL = 5
VK_OBJ = 6  # r_val = packed id of a child map (its makeMap's op id)
VK_TEXT = 7  # r_val = packed id of the document's text list

#: ROOT object encoding in packed object columns (0 means HEAD/empty)
OBJ_ROOT = -1

#: canonical column order of a map-register stream row (host encode ->
#: device kernel share this single definition)
MAP_STREAM_COLS = ("p_obj", "p_key", "p_op", "p_kind", "p_val")


class PackedDocs(NamedTuple):
    """Batched document state; leading axis D is the (shardable) doc axis.

    Slots [0, num_slots[d]) of doc d hold its elements in document order,
    tombstones included.
    """

    # element axis (D, S)
    elem_id: jnp.ndarray  # int32 packed (ctr << ACTOR_BITS | actor)
    char: jnp.ndarray  # int32 codepoint
    # tombstone table (D, T): packed ids of deleted elements (append-only;
    # slot-aligned deleted flags would go stale when later inserts shift
    # slots, so visibility is recomputed at read time instead)
    tomb_id: jnp.ndarray  # int32 packed (0 = empty row)
    # mark-op table (D, M)
    m_action: jnp.ndarray  # int32: MA_ADD / MA_REMOVE (0 = empty row)
    m_type: jnp.ndarray  # int32: schema.MARK_INDEX
    m_start_kind: jnp.ndarray  # int32 BK_*
    m_start_elem: jnp.ndarray  # int32 packed
    m_end_kind: jnp.ndarray  # int32
    m_end_elem: jnp.ndarray  # int32 packed
    m_op: jnp.ndarray  # int32 packed op id
    m_attr: jnp.ndarray  # int32 interned attr (url/comment id); 0 = none
    # map register table (D, R): LWW winner per (map object, key) —
    # makeMap / map set / map del without leaving the device path
    r_obj: jnp.ndarray  # int32 container object (OBJ_ROOT = root; row empty iff r_op == 0)
    r_key: jnp.ndarray  # int32 interned key
    r_op: jnp.ndarray  # int32 packed winning op id (0 = empty row)
    r_kind: jnp.ndarray  # int32 VK_*
    r_val: jnp.ndarray  # int32 payload per VK_*
    # scalars per doc (D,)
    num_slots: jnp.ndarray  # int32
    num_tombs: jnp.ndarray  # int32
    num_marks: jnp.ndarray  # int32
    num_regs: jnp.ndarray  # int32
    overflow: jnp.ndarray  # bool: capacity exceeded or invalid reference

    @property
    def num_docs(self) -> int:
        return self.elem_id.shape[0]

    @property
    def slot_capacity(self) -> int:
        return self.elem_id.shape[1]

    @property
    def tomb_capacity(self) -> int:
        return self.tomb_id.shape[1]

    @property
    def mark_capacity(self) -> int:
        return self.m_action.shape[1]

    @property
    def map_capacity(self) -> int:
        return self.r_obj.shape[1]


def empty_docs(
    num_docs: int,
    slot_capacity: int,
    mark_capacity: int,
    tomb_capacity: int | None = None,
    map_capacity: int = 32,
) -> PackedDocs:
    """Fresh empty batch (documents are built by applying their change logs)."""
    d, s, m = num_docs, slot_capacity, mark_capacity
    t = tomb_capacity if tomb_capacity is not None else s
    r = map_capacity
    zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
    return PackedDocs(
        elem_id=zi(d, s),
        char=zi(d, s),
        tomb_id=zi(d, t),
        m_action=zi(d, m),
        m_type=zi(d, m),
        m_start_kind=zi(d, m),
        m_start_elem=zi(d, m),
        m_end_kind=zi(d, m),
        m_end_elem=zi(d, m),
        m_op=zi(d, m),
        m_attr=zi(d, m),
        r_obj=zi(d, r),
        r_key=zi(d, r),
        r_op=zi(d, r),
        r_kind=zi(d, r),
        r_val=zi(d, r),
        num_slots=zi(d),
        num_tombs=zi(d),
        num_marks=zi(d),
        num_regs=zi(d),
        overflow=jnp.zeros((d,), bool),
    )


def to_numpy(state: PackedDocs) -> "PackedDocs":
    return PackedDocs(*(np.asarray(x) for x in state))
