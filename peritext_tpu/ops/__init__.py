"""Batched device path: packed state, op encoding, apply kernel, resolution."""

from .decode import decode_doc_spans, decode_doc_text
from .encode import EncodeResult, encode_workloads
from .kernel import apply_ops, apply_ops_jit, apply_ops_single
from .packed import PackedDocs, empty_docs
from .resolve import ResolvedDocs, resolve, resolve_jit

__all__ = [
    "PackedDocs",
    "empty_docs",
    "EncodeResult",
    "encode_workloads",
    "apply_ops",
    "apply_ops_jit",
    "apply_ops_single",
    "ResolvedDocs",
    "resolve",
    "resolve_jit",
    "decode_doc_spans",
    "decode_doc_text",
]
