"""Batched device path: packed state, op encoding, apply kernel, resolution."""

from .decode import decode_doc_spans, decode_doc_text
from .encode import EncodedBatch, encode_workloads
from .kernel import (
    apply_batch,
    apply_batch_jit,
    encoded_arrays_of,
)
from .packed import ACTOR_BITS, PackedDocs, empty_docs, pack_id, unpack_id
from .resolve import ResolvedDocs, resolve, resolve_jit

__all__ = [
    "PackedDocs",
    "empty_docs",
    "pack_id",
    "unpack_id",
    "ACTOR_BITS",
    "EncodedBatch",
    "encode_workloads",
    "apply_batch",
    "apply_batch_jit",
    "encoded_arrays_of",
    "ResolvedDocs",
    "resolve",
    "resolve_jit",
    "decode_doc_spans",
    "decode_doc_text",
]
