"""Batched CRDT op-application kernel.

Per document: a ``lax.fori_loop`` over its causally pre-ordered, padded op
stream; ``vmap`` over the doc axis (which is the sharded axis under a mesh).
Each op's work is a fixed set of masked vector primitives over the slot axis
— the reference's O(n) pointer-chasing scans (src/micromerge.ts:1304, :1334)
become O(S) lane-parallel compare/select/shift ops, which is the shape the
TPU VPU wants.  No data-dependent Python control flow: op dispatch is
``lax.switch``, loops are structural.

Semantics mirrored from the reference:
* insert: RGA insert-after-reference with the convergence skip past elements
  whose elemId exceeds the inserting op's ID (src/micromerge.ts:1201-1208);
  realized as "first non-blocked position right of the reference" via a
  masked argmin, then a masked shift-right of the slot arrays.
* delete: tombstone, idempotent (src/micromerge.ts:1261-1277); visibility is
  recomputed on read, so no splice is needed.
* addMark/removeMark: append to the grow-only mark table (span resolution
  happens at read time; see ops/resolve.py).

A reference element that cannot be found, or a capacity overflow, sets the
doc's ``overflow`` flag; the API layer falls back to the scalar oracle for
flagged docs (core/errors.CapacityExceeded).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .encode import (
    F_CHAR,
    F_KIND,
    F_OP_ACTOR,
    F_OP_CTR,
    F_REF_ACTOR,
    F_REF_CTR,
    F_START_KIND,
    F_START_CTR,
    F_START_ACTOR,
    F_END_KIND,
    F_END_CTR,
    F_END_ACTOR,
    F_MARK_TYPE,
    F_ATTR,
    K_ADD_MARK,
    K_REMOVE_MARK,
)
from .packed import MA_ADD, MA_REMOVE, PackedDocs


def _lex_gt(a_ctr, a_actor, b_ctr, b_actor):
    """(a_ctr, a_actor) > (b_ctr, b_actor) lexicographically."""
    return (a_ctr > b_ctr) | ((a_ctr == b_ctr) & (a_actor > b_actor))


def _apply_pad(state: PackedDocs, row: jnp.ndarray) -> PackedDocs:
    return state


def _apply_insert(state: PackedDocs, row: jnp.ndarray) -> PackedDocs:
    s_cap = state.elem_ctr.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)
    n = state.num_slots

    ref_ctr, ref_actor = row[F_REF_CTR], row[F_REF_ACTOR]
    op_ctr, op_actor = row[F_OP_CTR], row[F_OP_ACTOR]

    is_head = (ref_ctr == 0) & (ref_actor == 0)
    match = (state.elem_ctr == ref_ctr) & (state.elem_actor == ref_actor) & (pos < n)
    found = is_head | jnp.any(match)
    p = jnp.where(is_head, jnp.int32(-1), jnp.argmax(match).astype(jnp.int32))

    # RGA convergence skip: land at the first position right of the reference
    # whose element does NOT have a greater elemId than the inserting op.
    elem_gt_op = _lex_gt(state.elem_ctr, state.elem_actor, op_ctr, op_actor)
    candidate = (pos > p) & (pos < n) & ~elem_gt_op
    q = jnp.min(jnp.where(candidate, pos, n))

    def shifted(arr, new_value):
        rolled = jnp.roll(arr, 1)
        return jnp.where(pos < q, arr, jnp.where(pos == q, new_value, rolled))

    ok = found & (n < s_cap)

    def write(old, new):
        return jnp.where(ok, new, old)

    return state._replace(
        elem_ctr=write(state.elem_ctr, shifted(state.elem_ctr, op_ctr)),
        elem_actor=write(state.elem_actor, shifted(state.elem_actor, op_actor)),
        char=write(state.char, shifted(state.char, row[F_CHAR])),
        deleted=write(state.deleted, shifted(state.deleted, False)),
        num_slots=jnp.where(ok, n + 1, n),
        overflow=state.overflow | ~ok,
    )


def _apply_delete(state: PackedDocs, row: jnp.ndarray) -> PackedDocs:
    s_cap = state.elem_ctr.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)
    match = (
        (state.elem_ctr == row[F_REF_CTR])
        & (state.elem_actor == row[F_REF_ACTOR])
        & (pos < state.num_slots)
    )
    found = jnp.any(match)
    return state._replace(
        deleted=state.deleted | match,
        overflow=state.overflow | ~found,
    )


def _apply_mark(action: int, state: PackedDocs, row: jnp.ndarray) -> PackedDocs:
    m_cap = state.m_action.shape[0]
    mpos = jnp.arange(m_cap, dtype=jnp.int32)
    idx = state.num_marks
    at = mpos == idx  # matches nothing when idx >= m_cap

    def w(arr, value):
        return jnp.where(at, value, arr)

    return state._replace(
        m_action=w(state.m_action, jnp.int32(action)),
        m_type=w(state.m_type, row[F_MARK_TYPE]),
        m_start_kind=w(state.m_start_kind, row[F_START_KIND]),
        m_start_ctr=w(state.m_start_ctr, row[F_START_CTR]),
        m_start_actor=w(state.m_start_actor, row[F_START_ACTOR]),
        m_end_kind=w(state.m_end_kind, row[F_END_KIND]),
        m_end_ctr=w(state.m_end_ctr, row[F_END_CTR]),
        m_end_actor=w(state.m_end_actor, row[F_END_ACTOR]),
        m_op_ctr=w(state.m_op_ctr, row[F_OP_CTR]),
        m_op_actor=w(state.m_op_actor, row[F_OP_ACTOR]),
        m_attr=w(state.m_attr, row[F_ATTR]),
        num_marks=jnp.minimum(idx + 1, m_cap),
        overflow=state.overflow | (idx >= m_cap),
    )


def apply_ops_single(state: PackedDocs, ops: jnp.ndarray) -> PackedDocs:
    """Apply one document's padded op stream (K, NUM_FIELDS) sequentially."""

    branches = (
        _apply_pad,
        _apply_insert,
        _apply_delete,
        partial(_apply_mark, MA_ADD),
        partial(_apply_mark, MA_REMOVE),
    )

    def body(k, st):
        row = ops[k]
        return lax.switch(jnp.clip(row[F_KIND], 0, 4), branches, st, row)

    return lax.fori_loop(0, ops.shape[0], body, state)


#: Batched apply: vmap over the doc axis.  jit at the call site (api/batch.py)
#: so sharding constraints can be attached.
apply_ops = jax.vmap(apply_ops_single)


apply_ops_jit = jax.jit(apply_ops)
