"""Batched CRDT op-application kernel (two-phase, split-stream).

Phase structure per document (vmap over the doc axis, which is the sharded
axis under a mesh):

1. **Inserts** — the only sequential phase: a ``lax.fori_loop`` whose carry
   is exactly two (S,) arrays (packed element ids + characters) plus two
   scalars.  Each step realizes the reference's RGA insert-after-reference
   with its convergence skip (src/micromerge.ts:1187-1245): the O(n)
   pointer-chasing scans become O(S) lane-parallel compare/select, and the
   list splice becomes a masked shift.  Keeping the carry to 2 arrays is the
   point — the loop is HBM-bandwidth bound.
2. **Deletes** — tombstones are idempotent flag-sets that commute with each
   other and do not affect insert placement (the RGA skip compares only
   element ids), so the whole delete stream applies as ONE vectorized
   any-match over (S x KD) (reference applyListUpdate, :1250-1277; the
   visible-array splice is unnecessary — visibility is recomputed on read).
3. **Marks** — already encoded in mark-table layout host-side; appended with
   one masked scatter (span semantics live in ops/resolve.py).

A reference element that cannot be found, or a capacity overflow, sets the
doc's ``overflow`` flag; the API layer falls back to the scalar oracle for
flagged docs.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

# Devprof bucket plumbing at the kernel boundary (obs/devprof.py): each jit
# wrapper below derives the dispatch's shape-bucket key from the ACTUAL
# argument arrays plus the static kwargs — exactly the granularity of jax's
# compile cache, so the per-site distinct-shape count cross-checks the
# RecompileSentinel.  Guarded on ``GLOBAL_DEVPROF.enabled``: the disabled
# path costs one attribute check per dispatch.  Merge-scope modules import
# telemetry from ..obs only (the PR-3 facade invariant).
from ..obs import GLOBAL_DEVPROF, note_jit_dispatch as _note_dispatch
from .encode import EncodedBatch, MARK_COLS
from .packed import PackedDocs


def _insert_loop(elem_id, char, n0, overflow0, ins_ref, ins_op, ins_char):
    """Sequential RGA insert phase for one document."""
    s_cap = elem_id.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)

    def body(k, carry):
        elem, chars, n, ov = carry
        ref, op = ins_ref[k], ins_op[k]
        live = op != 0
        is_head = ref == 0
        match = (elem == ref) & (pos < n)
        found = is_head | jnp.any(match)
        p = jnp.where(is_head, jnp.int32(-1), jnp.argmax(match).astype(jnp.int32))

        # Convergence skip: first position right of the reference whose
        # element id is NOT greater than the inserting op's id.  Packed ids
        # make this a single integer compare.
        candidate = (pos > p) & (pos < n) & (elem < op)
        q = jnp.min(jnp.where(candidate, pos, n))

        ok = live & found & (n < s_cap)
        rolled_elem = jnp.roll(elem, 1)
        rolled_char = jnp.roll(chars, 1)
        new_elem = jnp.where(pos < q, elem, jnp.where(pos == q, op, rolled_elem))
        new_char = jnp.where(pos < q, chars, jnp.where(pos == q, ins_char[k], rolled_char))
        return (
            jnp.where(ok, new_elem, elem),
            jnp.where(ok, new_char, chars),
            jnp.where(ok, n + 1, n),
            ov | (live & ~found) | (live & (n >= s_cap)),
        )

    return lax.fori_loop(0, ins_op.shape[0], body, (elem_id, char, n0, overflow0))


def _append_rows(table, count, rows, rows_count):
    """Masked scatter appending ``rows`` (dict or single array) into append-only
    ``table`` at [count, count + rows_count); out-of-range writes drop.

    Keep the SCATTER formulation: round 5 tried a gather+select over the
    capacity axis (each table slot takes rows[j - count] when in range) on
    the theory that the vmapped scatter lowered badly, and a same-process
    A/B (scripts/append_ab.py) measured the gather 2.6x SLOWER on the
    batch_8k shape (35.7 -> 95.3 ms/apply) — the batched dynamic gather is
    what lowers badly on TPU, the batch-dim scatter is fine."""
    single = not isinstance(table, dict)
    tables = {"_": table} if single else table
    new_rows = {"_": rows} if single else rows
    cap = next(iter(tables.values())).shape[0]
    km = next(iter(new_rows.values())).shape[0]
    src = jnp.arange(km, dtype=jnp.int32)
    dst = count + src
    valid = src < rows_count
    dst = jnp.where(valid, dst, cap)
    out = {
        col: tables[col].at[dst].set(new_rows[col], mode="drop") for col in tables
    }
    overflow = count + rows_count > cap
    new_count = jnp.minimum(count + rows_count, cap)
    if single:
        return out["_"], new_count, overflow
    return out, new_count, overflow


def _apply_doc(state: PackedDocs, ins_ref, ins_op, ins_char, del_target, mark_rows, mark_count):
    elem, char, n, ov = _insert_loop(
        state.elem_id, state.char, state.num_slots, state.overflow,
        ins_ref, ins_op, ins_char,
    )
    return _post_insert_doc(
        state._replace(elem_id=elem, char=char, num_slots=n, overflow=ov),
        del_target, mark_rows, mark_count,
    )


def _post_insert_doc(state: PackedDocs, del_target, mark_rows, mark_count,
                     exists=None):
    """Phases 2+3 (deletes, marks) for one doc, after the insert phase.

    ``exists`` optionally carries a precomputed (KD,) target-exists mask so
    callers whose element planes do NOT live in ``state`` (the ragged pool
    walk, ops/ragged.py) can reuse these phases on a dummy-elem state; with
    it given, ``state.elem_id`` is never read."""
    elem, n, ov = state.elem_id, state.num_slots, state.overflow

    # Deletes: validate targets exist, then append to the tombstone table
    # (dedup against rows already there keeps re-delivery idempotent).
    live = del_target != 0
    if exists is None:
        exists = jnp.any(elem[:, None] == del_target[None, :], axis=0)  # (KD,)
    # Idempotence: skip targets already tombstoned in the carried-over table
    # AND duplicates within this stream (concurrent deletes of one char).
    kd = del_target.shape[0]
    dup_earlier = jnp.any(
        (del_target[None, :] == del_target[:, None])
        & (jnp.arange(kd)[:, None] < jnp.arange(kd)[None, :]),
        axis=0,
    )
    already = (
        jnp.any(state.tomb_id[:, None] == del_target[None, :], axis=0) | dup_earlier
    ) & live
    del_err = jnp.any(live & ~exists)
    keep = live & exists & ~already
    # compact kept targets to a dense prefix so the append is contiguous
    order = jnp.argsort(~keep, stable=True)  # kept rows first
    dense = jnp.where(keep[order], del_target[order], 0)
    tomb_id, num_tombs, tomb_ov = _append_rows(
        state.tomb_id, state.num_tombs, dense, jnp.sum(keep).astype(jnp.int32)
    )

    marks_in = {col: getattr(state, col) for col in MARK_COLS}
    marks_out, num_marks, mark_ov = _append_rows(
        marks_in, state.num_marks, mark_rows, mark_count
    )
    return state._replace(
        tomb_id=tomb_id,
        num_tombs=num_tombs,
        num_marks=num_marks,
        overflow=ov | del_err | tomb_ov | mark_ov,
        **marks_out,
    )




def _apply_map_doc(state: PackedDocs, p_obj, p_key, p_op, p_kind, p_val, count):
    """Phase 4: LWW upsert of map registers for one doc.

    The scalar semantics is core/doc.py ``_apply_op``'s map branch (reference
    src/micromerge.ts:1151-1175): per (object, key), the op with the largest
    id wins; ``del`` wins like any write (kind VK_DELETED).  Sequential over
    the round's map stream because an unseen key must append exactly one
    register row even when written twice in a round; winner choice itself is
    an order-independent max, so any causally-valid schedule converges."""
    cap = state.r_obj.shape[0]
    kp = p_op.shape[0]

    def body(i, carry):
        r_obj, r_key, r_op, r_kind, r_val, n, ov = carry
        live = (i < count) & (p_op[i] != 0)
        match = (r_op != 0) & (r_obj == p_obj[i]) & (r_key == p_key[i])
        exists = jnp.any(match)
        pos = jnp.where(exists, jnp.argmax(match), n).astype(jnp.int32)
        full = ~exists & (n >= cap)
        pos = jnp.minimum(pos, cap - 1)
        win = live & ~full & (p_op[i] > r_op[pos])
        r_obj = r_obj.at[pos].set(jnp.where(win, p_obj[i], r_obj[pos]))
        r_key = r_key.at[pos].set(jnp.where(win, p_key[i], r_key[pos]))
        r_op = r_op.at[pos].set(jnp.where(win, p_op[i], r_op[pos]))
        r_kind = r_kind.at[pos].set(jnp.where(win, p_kind[i], r_kind[pos]))
        r_val = r_val.at[pos].set(jnp.where(win, p_val[i], r_val[pos]))
        n = n + (live & ~exists & ~full).astype(jnp.int32)
        ov = ov | (live & full)
        return (r_obj, r_key, r_op, r_kind, r_val, n, ov)

    r_obj, r_key, r_op, r_kind, r_val, n, ov = lax.fori_loop(
        0, kp, body,
        (state.r_obj, state.r_key, state.r_op, state.r_kind, state.r_val,
         state.num_regs, state.overflow),
    )
    return state._replace(
        r_obj=r_obj, r_key=r_key, r_op=r_op, r_kind=r_kind, r_val=r_val,
        num_regs=n, overflow=ov,
    )


def apply_batch(
    state: PackedDocs,
    encoded_arrays,
    *,
    insert_impl: str = "auto",
    insert_loop_slots: int | None = None,
) -> PackedDocs:
    """Batched apply: vmap of the phase pipeline over the doc axis.

    ``encoded_arrays`` is the tuple
    (ins_ref, ins_op, ins_char, del_target, marks_dict, mark_count[,
    maps_dict, map_count]) with leading doc axes, as produced by
    :func:`encoded_arrays_of`; the 6-tuple form (no map stream) is accepted
    for callers without map ops.

    ``insert_impl`` selects the sequential-phase implementation:
    ``"auto"`` (pallas on TPU, lax elsewhere), ``"lax"``, ``"pallas"``, or
    ``"pallas_interpret"`` (CPU-debuggable pallas, for differential tests).
    ``insert_loop_slots`` optionally bounds the slot window the insert loop
    touches (see pallas_insert.insert_batch_pallas); ignored on the lax path.
    """
    if len(encoded_arrays) == 6:
        ins_ref, ins_op, ins_char, del_target, marks, mark_count = encoded_arrays
        maps, map_count = None, None
    else:
        (ins_ref, ins_op, ins_char, del_target, marks, mark_count,
         maps, map_count) = encoded_arrays
    impl = insert_impl
    if impl == "auto":
        impl = resolve_insert_impl(state.elem_id)
    if impl == "pallas":
        # Long-doc shapes whose resident state cannot fit VMEM take the lax
        # path (streams state through HBM; slower but unbounded).
        from .pallas_insert import effective_loop_slots, pallas_vmem_ok

        s_loop = effective_loop_slots(state.elem_id.shape[1], insert_loop_slots)
        if not pallas_vmem_ok(s_loop):
            impl = "lax"
    if impl in ("pallas", "pallas_interpret"):
        from .pallas_insert import insert_batch_pallas

        elem, char, n, ov = insert_batch_pallas(
            state.elem_id, state.char, state.num_slots, state.overflow,
            ins_ref, ins_op, ins_char,
            interpret=(impl == "pallas_interpret"),
            loop_slots=insert_loop_slots,
        )
        state = state._replace(elem_id=elem, char=char, num_slots=n, overflow=ov)
        state = jax.vmap(_post_insert_doc)(state, del_target, marks, mark_count)
    elif impl == "lax":
        state = jax.vmap(_apply_doc)(
            state, ins_ref, ins_op, ins_char, del_target, marks, mark_count
        )
    else:
        raise ValueError(f"unknown insert_impl: {insert_impl!r}")
    if maps is not None:
        state = jax.vmap(_apply_map_doc)(
            state, maps["p_obj"], maps["p_key"], maps["p_op"],
            maps["p_kind"], maps["p_val"], map_count,
        )
    return state


# -- paged storage (store/): gather-based apply through a page table --------
#
# The paged layout (store/paged.py) keeps the element planes in a global
# (N_pages, P) pool with per-doc page tables instead of a padded (D, S)
# batch.  The apply path gathers ONLY the dispatched docs' pages into a
# dense (B, G*P) group — G the group's power-of-two page-count bucket — runs
# the exact same phase pipeline (apply_batch; byte-identical math), and
# scatters the element pages + aux rows back.  Page 0 is the reserved NULL
# page: page-table padding slots gather zeros from it, their scatters all
# land on it, and the program re-zeroes it last so padding can never leak
# state between docs.  Per-round device work therefore scales with
# sum(touched docs x their own bucket width), not docs x widest-doc width.

#: PackedDocs fields that stay dense per-doc rows under the paged layout
#: (tombstones/marks/registers/scalars are small; the element planes are
#: where the padded waste lives)
PAGED_AUX_FIELDS = tuple(
    f for f in PackedDocs._fields if f not in ("elem_id", "char")
)


def paged_state_of(pool_elem, pool_char, aux, row_idx, page_rows) -> PackedDocs:
    """Dense (B, G*P) PackedDocs view of ``row_idx``'s docs, gathered from
    the page pool through ``page_rows`` (B, G) and the dense aux rows.
    Out-of-range padding in ``row_idx`` clamps (jit gather semantics) to a
    real row whose streams are all-zero no-ops at apply time."""
    b, g = page_rows.shape
    p = pool_elem.shape[1]
    elem = pool_elem[page_rows].reshape(b, g * p)
    char = pool_char[page_rows].reshape(b, g * p)
    sub = {f: a[row_idx] for f, a in zip(PAGED_AUX_FIELDS, aux)}
    return PackedDocs(elem_id=elem, char=char, **sub)


_gather_paged_jit = jax.jit(paged_state_of)


def gather_paged_state_jit(pool_elem, pool_char, aux, row_idx, page_rows) -> PackedDocs:
    """jit-compiled :func:`paged_state_of` — the materialization program the
    paged read/digest paths dispatch (one program per (B, G) bucket)."""
    args = (pool_elem, pool_char, aux, row_idx, page_rows)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch("gather_paged_state", _gather_paged_jit, args)
    return _gather_paged_jit(*args)


def apply_batch_paged(
    pool_elem,
    pool_char,
    aux,  # tuple of dense (D, ...) arrays in PAGED_AUX_FIELDS order
    row_idx,  # (B,) int32 doc rows (padding >= D: gathers clamp, scatters drop)
    page_rows,  # (B, G) int32 page ids (padding entries = 0, the null page)
    encoded_arrays,  # the apply_batch stream tuple with (B, ...) doc axes
    *,
    insert_impl: str = "auto",
    insert_loop_slots: int | None = None,
):
    """Gather-through-page-table apply: the paged twin of
    :func:`apply_batch`.  Returns ``(pool_elem, pool_char, aux)`` updated.

    The math is exactly :func:`apply_batch` on the gathered dense view, so
    a paged backend is byte-identical to the padded one by construction —
    the layouts differ only in where the slots live between rounds."""
    state = paged_state_of(pool_elem, pool_char, aux, row_idx, page_rows)
    state = apply_batch(
        state, encoded_arrays,
        insert_impl=insert_impl, insert_loop_slots=insert_loop_slots,
    )
    b, g = page_rows.shape
    p = pool_elem.shape[1]
    flat = page_rows.reshape(-1)
    pool_elem = pool_elem.at[flat].set(state.elem_id.reshape(b * g, p))
    pool_char = pool_char.at[flat].set(state.char.reshape(b * g, p))
    # padding page-table entries all scattered onto the null page; restore it
    pool_elem = pool_elem.at[0].set(0)
    pool_char = pool_char.at[0].set(0)
    aux = tuple(
        a.at[row_idx].set(getattr(state, f))
        for f, a in zip(PAGED_AUX_FIELDS, aux)
    )
    return pool_elem, pool_char, aux


_apply_batch_paged_jit = jax.jit(
    apply_batch_paged, static_argnames=("insert_impl", "insert_loop_slots")
)


def apply_batch_paged_jit(pool_elem, pool_char, aux, row_idx, page_rows,
                          encoded_arrays, *, insert_impl: str = "auto",
                          insert_loop_slots: int | None = None):
    """jit-compiled :func:`apply_batch_paged` (``"auto"`` resolved at the
    boundary from the pool arrays' placement, as in :func:`apply_batch_jit`)."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(pool_elem)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_paged", _apply_batch_paged_jit,
            (pool_elem, pool_char, aux, row_idx, page_rows, encoded_arrays),
            dict(insert_impl=insert_impl, insert_loop_slots=insert_loop_slots),
        )
    return _apply_batch_paged_jit(
        pool_elem, pool_char, aux, row_idx, page_rows, encoded_arrays,
        insert_impl=insert_impl, insert_loop_slots=insert_loop_slots,
    )


def apply_batch_paged_groups(
    pool_elem,
    pool_char,
    aux,
    group_inputs,  # tuple of per-group (row_idx, page_rows, encoded_arrays)
    *,
    loop_slots_seq,  # static tuple of per-group insert_loop_slots
    insert_impl: str = "auto",
):
    """One round's page-bucket groups chained inside ONE program — the
    paged half of the fused round pipeline.  Each per-group dispatch of
    :func:`apply_batch_paged` reads and functionally rewrites the WHOLE
    pool (the ``.at[].set`` scatter allocates a fresh pool copy per group
    without donation), so a round touching several buckets paid one pool
    copy per bucket; chained + donated (the jit wrapper donates all three
    pool operands), XLA updates the pool in place across every group."""
    if len(group_inputs) != len(loop_slots_seq):
        raise ValueError("paged groups: inputs/loop_slots length mismatch")
    for (row_idx, page_rows, encoded_arrays), loop_slots in zip(
            group_inputs, loop_slots_seq):
        pool_elem, pool_char, aux = apply_batch_paged(
            pool_elem, pool_char, aux, row_idx, page_rows, encoded_arrays,
            insert_impl=insert_impl, insert_loop_slots=loop_slots,
        )
    return pool_elem, pool_char, aux


_apply_paged_groups_jit = jax.jit(
    apply_batch_paged_groups,
    static_argnames=("loop_slots_seq", "insert_impl"),
    donate_argnums=(0, 1, 2),
)
_apply_paged_groups_jit_nodonate = jax.jit(
    apply_batch_paged_groups,
    static_argnames=("loop_slots_seq", "insert_impl"),
)


def apply_batch_paged_groups_jit(pool_elem, pool_char, aux, group_inputs, *,
                                 loop_slots_seq, insert_impl: str = "auto",
                                 donate: bool | None = None):
    """jit-compiled :func:`apply_batch_paged_groups`; the pool operands
    (``pool_elem``/``pool_char``/``aux``) are donated per
    :func:`resolve_state_donation` (or the explicit ``donate``) — rebind
    to the returned triple either way."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(pool_elem)
    if donate is None:
        donate = resolve_state_donation(pool_elem)
    fn = (_apply_paged_groups_jit if donate
          else _apply_paged_groups_jit_nodonate)
    statics = dict(loop_slots_seq=tuple(loop_slots_seq),
                   insert_impl=insert_impl)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_paged_groups", fn,
            (pool_elem, pool_char, aux, tuple(group_inputs)), statics,
        )
    return fn(
        pool_elem, pool_char, aux, tuple(group_inputs), **statics,
    )


def _pad_from_flat(flat, counts, width: int):
    """(N,) flat per-doc-concatenated values + (D,) counts -> (D, width)
    zero-padded rows, reconstructed on device with ONE gather (host->device
    transfer is proportional to real ops, not padded capacity)."""
    counts = counts.astype(jnp.int32)
    if flat.shape[0] == 0:  # a round with zero ops of this kind
        return jnp.zeros((counts.shape[0], width), jnp.int32)
    offsets = jnp.cumsum(counts) - counts
    idx = offsets[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    mask = jnp.arange(width, dtype=jnp.int32)[None, :] < counts[:, None]
    safe = jnp.clip(idx, 0, int(flat.shape[0]) - 1)
    return jnp.where(mask, flat[safe], 0)


def apply_batch_compact(
    state: PackedDocs,
    stream_counts,  # (n_ins, n_del, n_mark, n_map) each (D,) int32
    ins_flat,  # (ref, op, char) each (N_i,) int32
    del_flat,  # (N_d,) int32
    mark_flat,  # dict col -> (N_m,) int32 in MARK_COLS order
    map_flat=None,  # dict col -> (N_p,) int32, packed.MAP_STREAM_COLS (optional)
    *,
    widths,  # static (ki, kd, km[, kp]) padded stream widths
    insert_impl: str = "auto",
    insert_loop_slots: int | None = None,
) -> PackedDocs:
    """apply_batch over compactly-transferred streams.

    The padded (D, K) layout the kernel consumes is rebuilt on device from
    flat arrays; with a slow host link (the padded rows are mostly zeros)
    this cuts per-round transfer several-fold.  Flat arrays may carry
    power-of-two padding at the END (zero rows beyond sum(counts) are never
    gathered into a live slot)."""
    n_ins, n_del, n_mark = stream_counts[0], stream_counts[1], stream_counts[2]
    ki, kd, km = widths[0], widths[1], widths[2]
    ins_ref = _pad_from_flat(ins_flat[0], n_ins, ki)
    ins_op = _pad_from_flat(ins_flat[1], n_ins, ki)
    ins_char = _pad_from_flat(ins_flat[2], n_ins, ki)
    del_target = _pad_from_flat(del_flat, n_del, kd)
    marks = {col: _pad_from_flat(mark_flat[col], n_mark, km) for col in mark_flat}
    arrays = (ins_ref, ins_op, ins_char, del_target, marks,
              n_mark.astype(jnp.int32))
    if map_flat is not None:
        n_map = stream_counts[3]
        kp = widths[3]
        maps = {col: _pad_from_flat(map_flat[col], n_map, kp) for col in map_flat}
        arrays = arrays + (maps, n_map.astype(jnp.int32))
    return apply_batch(
        state,
        arrays,
        insert_impl=insert_impl,
        insert_loop_slots=insert_loop_slots,
    )


_apply_batch_compact_jit = jax.jit(
    apply_batch_compact,
    static_argnames=("widths", "insert_impl", "insert_loop_slots"),
)


def apply_batch_compact_jit(state, stream_counts, ins_flat, del_flat, mark_flat,
                            map_flat=None, *, widths, insert_impl: str = "auto",
                            insert_loop_slots: int | None = None) -> PackedDocs:
    """jit-compiled :func:`apply_batch_compact` (``"auto"`` resolved at the
    boundary, as in :func:`apply_batch_jit`)."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(state.elem_id)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_compact", _apply_batch_compact_jit,
            (state, stream_counts, ins_flat, del_flat, mark_flat, map_flat),
            dict(widths=widths, insert_impl=insert_impl,
                 insert_loop_slots=insert_loop_slots),
        )
    return _apply_batch_compact_jit(
        state, stream_counts, ins_flat, del_flat, mark_flat, map_flat,
        widths=widths, insert_impl=insert_impl,
        insert_loop_slots=insert_loop_slots,
    )


def apply_batch_compact_rounds(
    state: PackedDocs,
    rounds,  # tuple of per-round (stream_counts, ins_flat, del_flat, mark_flat, map_flat)
    *,
    widths_seq,  # static tuple of per-round widths tuples
    loop_slots_seq,  # static tuple of per-round insert_loop_slots
    insert_impl: str = "auto",
) -> PackedDocs:
    """K causally-ordered rounds chained inside ONE program.

    The axon platform charges ~11 ms per dispatch of the 21-leaf state
    program regardless of its compute (round-5 floor probe,
    scripts/apply_phase_cost.py --floor), so a drain with several pending
    rounds pays K floors when each round dispatches alone.  Chaining the
    rounds in one jit keeps per-round causal semantics bit-identical (the
    same apply_batch_compact sequence, just traced together) and pays the
    floor once.  Compile cache is keyed by the static
    (widths_seq, loop_slots_seq); the scheduler's pow-2 width bucketing
    keeps the variant count small."""
    if not (len(rounds) == len(widths_seq) == len(loop_slots_seq)):
        raise ValueError(
            f"rounds/widths_seq/loop_slots_seq length mismatch: "
            f"{len(rounds)}/{len(widths_seq)}/{len(loop_slots_seq)}"
        )
    for r, widths, loop_slots in zip(rounds, widths_seq, loop_slots_seq):
        counts, ins_flat, del_flat, mark_flat, map_flat = r
        state = apply_batch_compact(
            state, counts, ins_flat, del_flat, mark_flat, map_flat,
            widths=widths, insert_impl=insert_impl,
            insert_loop_slots=loop_slots,
        )
    return state


_apply_rounds_jit = jax.jit(
    apply_batch_compact_rounds,
    static_argnames=("widths_seq", "loop_slots_seq", "insert_impl"),
)


def apply_batch_staged_rounds(
    state: PackedDocs,
    counts_all,  # (K, 4, D) int32: per-round (ins, del, mark, map) counts
    ins_all,  # (ref, op, char) each (sum ins_lens,) int32
    del_all,  # (sum del_lens,) int32
    mark_all,  # dict col -> (sum mark_lens,) int32
    map_all,  # dict col -> (sum map_lens,) int32
    *,
    widths_seq,  # static tuple of per-round (ki, kd, km, kp)
    loop_slots_seq,  # static tuple of per-round insert_loop_slots
    ins_lens,  # static tuple: per-round pow-2 bucket of each flat stream —
    del_lens,  # the in-program slice boundaries (static starts, so XLA
    mark_lens,  # lowers them to free constant-offset slices)
    map_lens,
    insert_impl: str = "auto",
) -> PackedDocs:
    """K causally-ordered rounds from ONE staged tensor set (the fused
    device-resident round pipeline's apply half).

    Functionally :func:`apply_batch_compact_rounds`, but the host ships one
    concatenated tensor per stream kind for the WHOLE batch instead of ~20
    arrays per round: the per-round flat streams (each pow-2 padded to its
    static entry in ``*_lens``) concatenate along their only axis, and the
    per-doc count vectors stack into one (K, 4, D) tensor — so a deep drain
    pays one host->device staging transfer set and one dispatch no matter
    how many rounds it fused.  The jit wrapper donates ``state``: XLA
    updates the 21-leaf resident state in place instead of allocating (and
    copying) a fresh copy per commit."""
    if not (len(widths_seq) == len(loop_slots_seq) == counts_all.shape[0]
            == len(ins_lens) == len(del_lens) == len(mark_lens)
            == len(map_lens)):
        raise ValueError("staged rounds: per-round static/tensor length mismatch")
    io = do = mo = po = 0
    for r in range(len(widths_seq)):
        counts = tuple(counts_all[r, j] for j in range(4))
        li, ld, lm, lp = ins_lens[r], del_lens[r], mark_lens[r], map_lens[r]
        ins = tuple(a[io:io + li] for a in ins_all)
        dels = del_all[do:do + ld]
        marks = {c: a[mo:mo + lm] for c, a in mark_all.items()}
        maps = {c: a[po:po + lp] for c, a in map_all.items()}
        state = apply_batch_compact(
            state, counts, ins, dels, marks, maps,
            widths=widths_seq[r], insert_impl=insert_impl,
            insert_loop_slots=loop_slots_seq[r],
        )
        io, do, mo, po = io + li, do + ld, mo + lm, po + lp
    return state


def resolve_state_donation(*arrays, platform: str | None = None) -> bool:
    """Whether the fused-pipeline programs should DONATE their resident
    state operands, resolved from where the data lives (the
    :func:`resolve_insert_impl` sniffing discipline).

    On TPU donation is the point of the fused pipeline: XLA aliases the
    21-leaf state (or the page pool) in place instead of allocating and
    copying a fresh resident copy per commit, and dispatch stays async.
    On XLA CPU a donated dispatch BLOCKS until the donated input's pending
    producer has finished (measured ~40x the async dispatch wall: 4.3 ms
    vs 0.11 ms per commit on the smoke shape), which would serialize the
    exact host/device overlap the pipeline exists to create — so CPU runs
    the undonated twin of the same program."""
    if platform is None:
        for a in arrays:
            sharding = getattr(a, "sharding", None)
            device_set = getattr(sharding, "device_set", None)
            if device_set:
                platform = next(iter(device_set)).platform
                break
    if platform is None:
        platform = jax.default_backend()
    return platform == "tpu"


_STAGED_ROUNDS_STATICS = ("widths_seq", "loop_slots_seq", "ins_lens",
                          "del_lens", "mark_lens", "map_lens", "insert_impl")
_apply_staged_rounds_jit = jax.jit(
    apply_batch_staged_rounds,
    static_argnames=_STAGED_ROUNDS_STATICS,
    donate_argnums=0,
)
_apply_staged_rounds_jit_nodonate = jax.jit(
    apply_batch_staged_rounds,
    static_argnames=_STAGED_ROUNDS_STATICS,
)


def apply_batch_staged_rounds_jit(state, counts_all, ins_all, del_all,
                                  mark_all, map_all, *, widths_seq,
                                  loop_slots_seq, ins_lens, del_lens,
                                  mark_lens, map_lens,
                                  insert_impl: str = "auto",
                                  donate: bool | None = None) -> PackedDocs:
    """jit-compiled :func:`apply_batch_staged_rounds`.  With ``donate``
    (default: :func:`resolve_state_donation`) the caller's input state
    buffer is consumed in place (reads of the old reference raise) —
    rebind to the returned state either way.  ``"auto"`` resolves at the
    boundary, as in :func:`apply_batch_jit`."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(state.elem_id)
    if donate is None:
        donate = resolve_state_donation(state.elem_id)
    fn = _apply_staged_rounds_jit if donate else _apply_staged_rounds_jit_nodonate
    statics = dict(widths_seq=tuple(widths_seq),
                   loop_slots_seq=tuple(loop_slots_seq),
                   ins_lens=tuple(ins_lens), del_lens=tuple(del_lens),
                   mark_lens=tuple(mark_lens), map_lens=tuple(map_lens),
                   insert_impl=insert_impl)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_staged_rounds", fn,
            (state, counts_all, ins_all, del_all, mark_all, map_all), statics,
        )
    return fn(
        state, counts_all, ins_all, del_all, mark_all, map_all, **statics,
    )


def apply_batch_stacked_rounds(
    state: PackedDocs,
    stacked,  # the apply_batch 8-tuple with a leading round axis R
    *,
    loop_slots_seq,  # static tuple of per-round insert_loop_slots
    insert_impl: str = "auto",
) -> PackedDocs:
    """K rounds of the PADDED (D, K) apply chained in one donated program —
    the fused pipeline's static-rounds form (serve/ shape discipline: every
    round at the session's fixed widths, so the only variant axes are the
    fused depth R and the log2 slot-window ladder)."""
    (ins_ref, ins_op, ins_char, del_t, marks, mark_count, maps,
     map_count) = stacked
    for r in range(len(loop_slots_seq)):
        arrays = (
            ins_ref[r], ins_op[r], ins_char[r], del_t[r],
            {c: a[r] for c, a in marks.items()}, mark_count[r],
            {c: a[r] for c, a in maps.items()}, map_count[r],
        )
        state = apply_batch(
            state, arrays, insert_impl=insert_impl,
            insert_loop_slots=loop_slots_seq[r],
        )
    return state


_apply_stacked_rounds_jit = jax.jit(
    apply_batch_stacked_rounds,
    static_argnames=("loop_slots_seq", "insert_impl"),
    donate_argnums=0,
)
_apply_stacked_rounds_jit_nodonate = jax.jit(
    apply_batch_stacked_rounds,
    static_argnames=("loop_slots_seq", "insert_impl"),
)


def apply_batch_stacked_rounds_jit(state, stacked, *, loop_slots_seq,
                                   insert_impl: str = "auto",
                                   donate: bool | None = None) -> PackedDocs:
    """jit-compiled :func:`apply_batch_stacked_rounds`; ``state`` donated
    per :func:`resolve_state_donation` (or the explicit ``donate``)."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(state.elem_id)
    if donate is None:
        donate = resolve_state_donation(state.elem_id)
    fn = (_apply_stacked_rounds_jit if donate
          else _apply_stacked_rounds_jit_nodonate)
    statics = dict(loop_slots_seq=tuple(loop_slots_seq),
                   insert_impl=insert_impl)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_stacked_rounds", fn, (state, stacked), statics,
        )
    return fn(state, stacked, **statics)


def _scatter_tenant_blocks(blocks, row_base, docs: int):
    """Per-tenant row blocks -> one (docs, ...) staging plane, in-program.

    ``blocks`` is (T, Dt, ...) — tenant t's Dt doc rows of one staging
    plane — and ``row_base`` is a (T,) int32 DATA plane: tenant t's rows
    land at ``row_base[t] + arange(Dt)``.  Scatter-ADD into zeros, not
    dynamic-update-slice, on purpose: all-zero rows are no-op rows to the
    apply phases, so a zero PAD block (T is pow-2 bucketed to keep one
    compile shape while the active-tenant subset varies as data) adds
    nothing wherever its row_base points, and overlapping pad targets
    stay harmless.  Tenant blocks themselves never alias — the fusion
    plan hands every tenant a disjoint doc-row range."""
    t, dt = blocks.shape[0], blocks.shape[1]
    rows = (row_base[:, None]
            + jnp.arange(dt, dtype=jnp.int32)[None, :]).reshape(-1)
    flat = blocks.reshape((t * dt,) + blocks.shape[2:])
    out = jnp.zeros((docs,) + blocks.shape[2:], blocks.dtype)
    return out.at[rows].add(flat)


def apply_batch_stacked_rounds_multi(
    state: PackedDocs,
    stacked,  # the apply_batch 8-tuple, leaves shaped (R, T, Dt, ...)
    row_base,  # (T,) int32 data plane: per-tenant doc-row offsets
    *,
    docs: int,  # static: the session's padded doc axis
    loop_slots_seq,  # static tuple of per-round insert_loop_slots
    insert_impl: str = "auto",
) -> PackedDocs:
    """The multi-tenant doc-row-offset form of
    :func:`apply_batch_stacked_rounds` (cross-tenant fusion, plan/).

    A fusion window usually touches a SUBSET of a lane's tenants; staging
    the lane's full (D, K) planes would ship mostly zeros.  This entry
    point ships only the active tenants' row blocks — (R, T, Dt, ...) per
    staging plane — plus ``row_base``, and rebuilds the full-width planes
    in-program via :func:`_scatter_tenant_blocks` before chaining the
    same per-round padded apply the stacked form runs.  ``row_base`` is
    DATA, so which tenants are active never recompiles; only the (T, Dt)
    block shape is static, and T pow-2 bucketing keeps that a ladder."""
    (ins_ref, ins_op, ins_char, del_t, marks, mark_count, maps,
     map_count) = stacked
    for r in range(len(loop_slots_seq)):
        def sc(plane, _r=r):
            return _scatter_tenant_blocks(plane[_r], row_base, docs)

        arrays = (
            sc(ins_ref), sc(ins_op), sc(ins_char), sc(del_t),
            {c: sc(a) for c, a in marks.items()}, sc(mark_count),
            {c: sc(a) for c, a in maps.items()}, sc(map_count),
        )
        state = apply_batch(
            state, arrays, insert_impl=insert_impl,
            insert_loop_slots=loop_slots_seq[r],
        )
    return state


_STACKED_MULTI_STATICS = ("docs", "loop_slots_seq", "insert_impl")
_apply_stacked_multi_jit = jax.jit(
    apply_batch_stacked_rounds_multi,
    static_argnames=_STACKED_MULTI_STATICS,
    donate_argnums=0,
)
_apply_stacked_multi_jit_nodonate = jax.jit(
    apply_batch_stacked_rounds_multi,
    static_argnames=_STACKED_MULTI_STATICS,
)


def apply_batch_stacked_rounds_multi_jit(
        state, stacked, row_base, *, loop_slots_seq,
        insert_impl: str = "auto", donate: bool | None = None) -> PackedDocs:
    """jit-compiled :func:`apply_batch_stacked_rounds_multi`; ``state``
    donated per :func:`resolve_state_donation` (or the explicit
    ``donate``)."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(state.elem_id)
    if donate is None:
        donate = resolve_state_donation(state.elem_id)
    fn = (_apply_stacked_multi_jit if donate
          else _apply_stacked_multi_jit_nodonate)
    statics = dict(docs=int(state.elem_id.shape[0]),
                   loop_slots_seq=tuple(loop_slots_seq),
                   insert_impl=insert_impl)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_stacked_rounds_multi", fn,
            (state, stacked, row_base), statics,
        )
    return fn(state, stacked, row_base, **statics)


def apply_batch_compact_rounds_jit(state, rounds, *, widths_seq,
                                   loop_slots_seq,
                                   insert_impl: str = "auto") -> PackedDocs:
    """jit-compiled :func:`apply_batch_compact_rounds` (``"auto"`` resolved
    at the boundary, as in :func:`apply_batch_jit`)."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(state.elem_id)
    rounds = tuple(rounds)
    statics = dict(widths_seq=tuple(widths_seq),
                   loop_slots_seq=tuple(loop_slots_seq),
                   insert_impl=insert_impl)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch_compact_rounds", _apply_rounds_jit,
            (state, rounds), statics,
        )
    return _apply_rounds_jit(state, rounds, **statics)


def encoded_arrays_of(encoded: EncodedBatch):
    """The device-array tuple for apply_batch from a host EncodedBatch.

    Emits the 8-tuple (with the map-register stream) when the source carries
    one — both EncodedBatch and the streaming round buffers do; sources
    without a ``map_ops`` attribute yield the 6-tuple form apply_batch
    equally accepts."""
    base = (
        jnp.asarray(encoded.ins_ref),
        jnp.asarray(encoded.ins_op),
        jnp.asarray(encoded.ins_char),
        jnp.asarray(encoded.del_target),
        {col: jnp.asarray(arr) for col, arr in sorted(encoded.marks.items())},
        jnp.asarray(encoded.mark_count),
    )
    map_ops = getattr(encoded, "map_ops", None)
    if map_ops is None:
        return base
    return base + (
        {col: jnp.asarray(arr) for col, arr in sorted(map_ops.items())},
        jnp.asarray(encoded.map_count),
    )


def resolve_insert_impl(*arrays, platform: str | None = None) -> str:
    """Pick the insert-phase implementation for where the data actually lives.

    ``jax.default_backend()`` alone is wrong on machines where a TPU plugin is
    the default platform but the computation targets a CPU mesh (the driver's
    multi-chip dry run uses ``--xla_force_host_platform_device_count`` virtual
    CPU devices while a real TPU stays registered): Pallas TPU kernels cannot
    lower for CPU.  So prefer the platform of the concrete input arrays'
    shardings; tracers carry no devices, so under an outer jit fall back to
    the default backend — callers jitting over a non-default mesh must pass
    ``insert_impl`` explicitly.
    """
    if platform is None:
        for a in arrays:
            sharding = getattr(a, "sharding", None)
            device_set = getattr(sharding, "device_set", None)
            if device_set:
                platform = next(iter(device_set)).platform
                break
    if platform is None:
        platform = jax.default_backend()
    return "pallas" if platform == "tpu" else "lax"


def resolve_ragged_impl(*arrays, platform: str | None = None) -> str:
    """Pick the ragged pool-walk implementation (ops/ragged.py) for where
    the pool actually lives — the :func:`resolve_insert_impl` sniffing
    discipline, with the same pallas-iff-TPU outcome: ``"pallas"`` walks
    pages with the ragged Pallas grid, ``"lax"`` is the dense pool-walk
    fallback every CPU path (tier-1, interpret smokes) runs."""
    if platform is None:
        for a in arrays:
            sharding = getattr(a, "sharding", None)
            device_set = getattr(sharding, "device_set", None)
            if device_set:
                platform = next(iter(device_set)).platform
                break
    if platform is None:
        platform = jax.default_backend()
    return "pallas" if platform == "tpu" else "lax"


_apply_batch_jit = jax.jit(
    apply_batch, static_argnames=("insert_impl", "insert_loop_slots")
)


def apply_batch_jit(
    state: PackedDocs,
    encoded_arrays,
    *,
    insert_impl: str = "auto",
    insert_loop_slots: int | None = None,
) -> PackedDocs:
    """jit-compiled :func:`apply_batch`, resolving ``"auto"`` at the jit
    boundary where input shardings are still observable."""
    if insert_impl == "auto":
        insert_impl = resolve_insert_impl(state.elem_id)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch(
            "apply_batch", _apply_batch_jit, (state, encoded_arrays),
            dict(insert_impl=insert_impl, insert_loop_slots=insert_loop_slots),
        )
    return _apply_batch_jit(
        state,
        encoded_arrays,
        insert_impl=insert_impl,
        insert_loop_slots=insert_loop_slots,
    )
