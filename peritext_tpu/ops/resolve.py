"""Read-time span resolution: mark table -> per-character mark state.

The convergent semantics (see ops/packed.py): a mark op covers character ``x``
iff, in the final element order, the op's start anchor position is <= the gap
just before ``x`` and its end anchor position is > that gap.  Anchor positions
live on the 2n+2 gap grid: ``before(e) -> 2*idx(e)``, ``after(e) ->
2*idx(e)+1``, ``startOfText -> -1``, ``endOfText -> +inf`` (reference
BoundaryPosition, src/micromerge.ts:266-270; this is the pure form of the
reference's materialized-gap walk :1002-1138).

Winners are resolved per mark type exactly as core/spans.ops_to_marks:
last-writer-wins by op id for strong/em/link, per-comment-id LWW for
comments — packed ids make every winner comparison a single integer max.
Realized as a ``fori_loop`` over the mark table maintaining running winner
state per slot: O(S) (and O(C x S) for comments) memory; no (M x S) cover
matrix is ever materialized.

Visibility is also computed here: a slot is visible iff occupied and its
element id is absent from the tombstone table (one vectorized any-match).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..schema import ALL_MARKS, MARK_INDEX
from .packed import (
    BK_BEFORE,
    BK_END_OF_TEXT,
    BK_START_OF_TEXT,
    MA_ADD,
    PackedDocs,
)

NUM_TYPES = len(ALL_MARKS)
COMMENT_TYPE = MARK_INDEX["comment"]


class ResolvedDocs(NamedTuple):
    """Per-character resolved formatting for a batch of docs."""

    char: jnp.ndarray  # int32 (D, S)
    visible: jnp.ndarray  # bool (D, S)
    #: (D, T, S): winning op is an addMark, per LWW mark type T
    lww_active: jnp.ndarray
    #: (D, S): interned url of the winning link op (0 = none)
    link_attr: jnp.ndarray
    #: (D, C, S): per interned comment id, winning op is an addMark
    comment_active: jnp.ndarray
    overflow: jnp.ndarray  # bool (D,)


def _anchor_gap(elem_id, kind, anchor, pos, n, big):
    """Gap-grid position of a boundary anchor; element matched over slots."""
    match = (elem_id == anchor) & (pos < n)
    idx = jnp.argmax(match).astype(jnp.int32)
    found = jnp.any(match)
    elem_gap = jnp.where(kind == BK_BEFORE, 2 * idx, 2 * idx + 1)
    gap = jnp.where(
        kind == BK_START_OF_TEXT,
        jnp.int32(-1),
        jnp.where(kind == BK_END_OF_TEXT, big, elem_gap),
    )
    anchored = (kind == BK_START_OF_TEXT) | (kind == BK_END_OF_TEXT) | found
    return gap, anchored


def resolve_single(state: PackedDocs, comment_capacity: int) -> ResolvedDocs:
    """Resolve one document (unbatched arrays)."""
    s_cap = state.elem_id.shape[0]
    m_cap = state.m_action.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)
    n = state.num_slots
    big = jnp.int32(2 * s_cap + 1)
    gap_before = 2 * pos  # the gap governing each slot's character

    class Carry(NamedTuple):
        best_op: jnp.ndarray  # (T, S) packed id of winning op per LWW type
        best_add: jnp.ndarray  # (T, S) bool
        best_attr: jnp.ndarray  # (T, S) int32 (only the link row is read)
        c_op: jnp.ndarray  # (C, S)
        c_add: jnp.ndarray  # (C, S) bool
        error: jnp.ndarray  # () bool

    carry = Carry(
        best_op=jnp.zeros((NUM_TYPES, s_cap), jnp.int32),
        best_add=jnp.zeros((NUM_TYPES, s_cap), bool),
        best_attr=jnp.zeros((NUM_TYPES, s_cap), jnp.int32),
        c_op=jnp.zeros((comment_capacity, s_cap), jnp.int32),
        c_add=jnp.zeros((comment_capacity, s_cap), bool),
        error=jnp.asarray(False),
    )

    def body(m, carry: Carry) -> Carry:
        live = state.m_action[m] != 0
        s_gap, s_ok = _anchor_gap(
            state.elem_id, state.m_start_kind[m], state.m_start_elem[m], pos, n, big
        )
        e_gap, e_ok = _anchor_gap(
            state.elem_id, state.m_end_kind[m], state.m_end_elem[m], pos, n, big
        )
        cover = live & (s_gap <= gap_before) & (gap_before < e_gap) & (pos < n)

        op = state.m_op[m]
        is_add = state.m_action[m] == MA_ADD
        mtype = state.m_type[m]
        attr = state.m_attr[m]

        # LWW winner update for this op's type row (packed id max).
        type_row = (jnp.arange(NUM_TYPES, dtype=jnp.int32) == mtype)[:, None]
        upd = type_row & cover[None, :] & (op > carry.best_op) & (mtype != COMMENT_TYPE)
        best_op = jnp.where(upd, op, carry.best_op)
        best_add = jnp.where(upd, is_add, carry.best_add)
        best_attr = jnp.where(upd, attr, carry.best_attr)

        # Per-comment-id winner update (row = interned attr id).
        c_row = (jnp.arange(comment_capacity, dtype=jnp.int32) == attr)[:, None]
        c_upd = c_row & cover[None, :] & (op > carry.c_op) & (mtype == COMMENT_TYPE)
        c_op = jnp.where(c_upd, op, carry.c_op)
        c_add = jnp.where(c_upd, is_add, carry.c_add)

        error = carry.error | (live & ~(s_ok & e_ok))
        error = error | (live & (mtype == COMMENT_TYPE) & (attr >= comment_capacity))
        return Carry(best_op, best_add, best_attr, c_op, c_add, error)

    out = lax.fori_loop(0, m_cap, body, carry)

    # Visibility: occupied and not tombstoned (one vectorized any-match).
    tombed = jnp.any(
        (state.elem_id[:, None] == state.tomb_id[None, :]) & (state.tomb_id != 0)[None, :],
        axis=1,
    )
    visible = (pos < n) & ~tombed

    return ResolvedDocs(
        char=state.char,
        visible=visible,
        lww_active=out.best_add,
        link_attr=jnp.where(
            out.best_add[MARK_INDEX["link"]], out.best_attr[MARK_INDEX["link"]], 0
        ),
        comment_active=out.c_add,
        overflow=state.overflow | out.error,
    )


def resolve(state: PackedDocs, comment_capacity: int = 32) -> ResolvedDocs:
    """Batched resolution over the doc axis."""
    return jax.vmap(lambda s: resolve_single(s, comment_capacity))(state)


resolve_jit = jax.jit(resolve, static_argnums=1)
