"""Read-time span resolution: mark table -> per-character mark state.

The convergent semantics (see ops/packed.py): a mark op covers character ``x``
iff, in the final element order, the op's start anchor position is <= the gap
just before ``x`` and its end anchor position is > that gap.  Anchor positions
live on the 2n+2 gap grid: ``before(e) -> 2*idx(e)``, ``after(e) ->
2*idx(e)+1``, ``startOfText -> -1``, ``endOfText -> +inf`` (reference
BoundaryPosition, src/micromerge.ts:266-270; this is the pure form of the
reference's materialized-gap walk :1002-1138).

Winners are resolved per mark type exactly as core/spans.ops_to_marks:
last-writer-wins by (ctr, actor) for strong/em/link, per-comment-id LWW for
comments.  Realized as a ``fori_loop`` over the mark table maintaining running
lexicographic-max winner state per slot — O(S) (and O(C x S) for comments)
memory, no (M x S) cover matrix is ever materialized.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..schema import ALL_MARKS, MARK_INDEX
from .packed import (
    BK_AFTER,
    BK_BEFORE,
    BK_END_OF_TEXT,
    BK_START_OF_TEXT,
    MA_ADD,
    PackedDocs,
)

NUM_LWW_TYPES = len(ALL_MARKS)  # winner tracked per type; comments use rows too
COMMENT_TYPE = MARK_INDEX["comment"]


class ResolvedDocs(NamedTuple):
    """Per-character resolved formatting for a batch of docs."""

    char: jnp.ndarray  # int32 (D, S)
    visible: jnp.ndarray  # bool (D, S)
    #: (D, T, S): winning op is an addMark, per LWW mark type T
    lww_active: jnp.ndarray
    #: (D, S): interned url of the winning link op (0 = none)
    link_attr: jnp.ndarray
    #: (D, C, S): per interned comment id, winning op is an addMark
    comment_active: jnp.ndarray
    overflow: jnp.ndarray  # bool (D,)


def _anchor_gap(state: PackedDocs, kind, ctr, actor, pos, n):
    """Gap-grid position of a boundary anchor; elements matched over slots."""
    match = (state.elem_ctr == ctr) & (state.elem_actor == actor) & (pos < n)
    idx = jnp.argmax(match).astype(jnp.int32)
    found = jnp.any(match)
    elem_gap = jnp.where(kind == BK_BEFORE, 2 * idx, 2 * idx + 1)
    big = jnp.int32(2 * state.elem_ctr.shape[0] + 1)
    gap = jnp.where(
        kind == BK_START_OF_TEXT,
        jnp.int32(-1),
        jnp.where(kind == BK_END_OF_TEXT, big, elem_gap),
    )
    anchored = (kind == BK_START_OF_TEXT) | (kind == BK_END_OF_TEXT) | found
    return gap, anchored


def resolve_single(state: PackedDocs, comment_capacity: int) -> ResolvedDocs:
    """Resolve one document (unbatched arrays)."""
    s_cap = state.elem_ctr.shape[0]
    m_cap = state.m_action.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)
    n = state.num_slots
    gap_before = 2 * pos  # the gap governing each slot's character

    class Carry(NamedTuple):
        best_ctr: jnp.ndarray  # (T, S)
        best_actor: jnp.ndarray  # (T, S)
        best_add: jnp.ndarray  # (T, S) bool
        best_attr: jnp.ndarray  # (T, S) int32 (only the link row is read)
        c_ctr: jnp.ndarray  # (C, S)
        c_actor: jnp.ndarray  # (C, S)
        c_add: jnp.ndarray  # (C, S) bool
        error: jnp.ndarray  # () bool

    t_shape = (NUM_LWW_TYPES, s_cap)
    c_shape = (comment_capacity, s_cap)
    carry = Carry(
        best_ctr=jnp.full(t_shape, -1, jnp.int32),
        best_actor=jnp.full(t_shape, -1, jnp.int32),
        best_add=jnp.zeros(t_shape, bool),
        best_attr=jnp.zeros(t_shape, jnp.int32),
        c_ctr=jnp.full(c_shape, -1, jnp.int32),
        c_actor=jnp.full(c_shape, -1, jnp.int32),
        c_add=jnp.zeros(c_shape, bool),
        error=jnp.asarray(False),
    )

    def body(m, carry: Carry) -> Carry:
        live = state.m_action[m] != 0
        s_gap, s_ok = _anchor_gap(
            state, state.m_start_kind[m], state.m_start_ctr[m], state.m_start_actor[m], pos, n
        )
        e_gap, e_ok = _anchor_gap(
            state, state.m_end_kind[m], state.m_end_ctr[m], state.m_end_actor[m], pos, n
        )
        cover = live & (s_gap <= gap_before) & (gap_before < e_gap) & (pos < n)  # (S,)

        op_ctr, op_actor = state.m_op_ctr[m], state.m_op_actor[m]
        is_add = state.m_action[m] == MA_ADD
        mtype = state.m_type[m]
        attr = state.m_attr[m]

        # LWW winner update for this op's type row.
        type_row = (jnp.arange(NUM_LWW_TYPES, dtype=jnp.int32) == mtype)[:, None]
        newer = (op_ctr > carry.best_ctr) | (
            (op_ctr == carry.best_ctr) & (op_actor > carry.best_actor)
        )
        upd = type_row & cover[None, :] & newer & (mtype != COMMENT_TYPE)
        best_ctr = jnp.where(upd, op_ctr, carry.best_ctr)
        best_actor = jnp.where(upd, op_actor, carry.best_actor)
        best_add = jnp.where(upd, is_add, carry.best_add)
        best_attr = jnp.where(upd, attr, carry.best_attr)

        # Per-comment-id winner update (row = interned attr id).
        c_row = (jnp.arange(comment_capacity, dtype=jnp.int32) == attr)[:, None]
        c_newer = (op_ctr > carry.c_ctr) | (
            (op_ctr == carry.c_ctr) & (op_actor > carry.c_actor)
        )
        c_upd = c_row & cover[None, :] & c_newer & (mtype == COMMENT_TYPE)
        c_ctr = jnp.where(c_upd, op_ctr, carry.c_ctr)
        c_actor = jnp.where(c_upd, op_actor, carry.c_actor)
        c_add = jnp.where(c_upd, is_add, carry.c_add)

        error = carry.error | (live & ~(s_ok & e_ok))
        error = error | (live & (mtype == COMMENT_TYPE) & (attr >= comment_capacity))
        return Carry(best_ctr, best_actor, best_add, best_attr, c_ctr, c_actor, c_add, error)

    out = lax.fori_loop(0, m_cap, body, carry)

    visible = (pos < n) & ~state.deleted
    return ResolvedDocs(
        char=state.char,
        visible=visible,
        lww_active=out.best_add,
        link_attr=jnp.where(
            out.best_add[MARK_INDEX["link"]], out.best_attr[MARK_INDEX["link"]], 0
        ),
        comment_active=out.c_add,
        overflow=state.overflow | out.error,
    )


def resolve(state: PackedDocs, comment_capacity: int = 32) -> ResolvedDocs:
    """Batched resolution over the doc axis."""
    return jax.vmap(lambda s: resolve_single(s, comment_capacity))(state)


resolve_jit = jax.jit(resolve, static_argnums=1)
