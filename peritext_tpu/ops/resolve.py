"""Read-time span resolution: mark table -> per-character mark state.

The convergent semantics (see ops/packed.py): a mark op covers character ``x``
iff, in the final element order, the op's start anchor position is <= the gap
just before ``x`` and its end anchor position is > that gap.  Anchor positions
live on the 2n+2 gap grid: ``before(e) -> 2*idx(e)``, ``after(e) ->
2*idx(e)+1``, ``startOfText -> -1``, ``endOfText -> +inf`` (reference
BoundaryPosition, src/micromerge.ts:266-270; this is the pure form of the
reference's materialized-gap walk :1002-1138).

Winner resolution per character follows core/spans.ops_to_marks: the governing
op per mark type is the max op id among covering ops (LWW for strong/em/link,
per-comment-id for comments).  Because max is associative, the mark table is
consumed in CHUNKS of ``MARK_CHUNK`` rows: each ``fori_loop`` iteration
reduces its chunk's covering ops to per-slot add/remove maxima and combines
them into the carried running maxima.  A character is marked iff its max
covering *add* op beats its max covering *remove* op — so no winner-action or
winner-id bookkeeping is carried at all, which (together with chunking)
cuts the loop-carried HBM traffic by more than an order of magnitude versus
a per-mark walk.  Padding chunk reads may overlap the previous chunk
(dynamic_slice clamps); that is harmless because max/or updates are
idempotent.

Visibility is also computed here: a slot is visible iff occupied and its
element id is absent from the tombstone table (one vectorized any-match).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..schema import ALL_MARKS, MARK_INDEX
from .packed import (
    BK_BEFORE,
    BK_END_OF_TEXT,
    BK_START_OF_TEXT,
    MA_ADD,
    MA_REMOVE,
    PackedDocs,
)

NUM_TYPES = len(ALL_MARKS)
COMMENT_TYPE = MARK_INDEX["comment"]
LINK_TYPE = MARK_INDEX["link"]
MARK_CHUNK = 32


class ResolvedDocs(NamedTuple):
    """Per-character resolved formatting for a batch of docs."""

    char: jnp.ndarray  # int32 (D, S)
    visible: jnp.ndarray  # bool (D, S)
    #: (D, T, S): winning op is an addMark, per LWW mark type T
    lww_active: jnp.ndarray
    #: (D, S): interned url of the winning link op (0 = none)
    link_attr: jnp.ndarray
    #: (D, C, S): per interned comment id, winning op is an addMark
    comment_active: jnp.ndarray
    overflow: jnp.ndarray  # bool (D,)


def resolve_single(state: PackedDocs, comment_capacity: int) -> ResolvedDocs:
    """Resolve one document (unbatched arrays)."""
    s_cap = state.elem_id.shape[0]
    m_cap = state.m_action.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)
    n = state.num_slots
    big = jnp.int32(2 * s_cap + 1)
    gap_before = 2 * pos  # the gap governing each slot's character

    class Carry(NamedTuple):
        add_op: jnp.ndarray  # (T, S) max covering add-op id per LWW type
        rem_op: jnp.ndarray  # (T, S) max covering remove-op id
        link_attr: jnp.ndarray  # (S,) attr of the current best link add op
        c_add_op: jnp.ndarray  # (C, S) per interned comment id
        c_rem_op: jnp.ndarray  # (C, S)
        error: jnp.ndarray  # () bool

    carry = Carry(
        add_op=jnp.zeros((NUM_TYPES, s_cap), jnp.int32),
        rem_op=jnp.zeros((NUM_TYPES, s_cap), jnp.int32),
        link_attr=jnp.zeros((s_cap,), jnp.int32),
        c_add_op=jnp.zeros((comment_capacity, s_cap), jnp.int32),
        c_rem_op=jnp.zeros((comment_capacity, s_cap), jnp.int32),
        error=jnp.asarray(False),
    )

    chunk = max(1, min(MARK_CHUNK, m_cap))

    def body(j, carry: Carry) -> Carry:
        row = lambda a: lax.dynamic_slice_in_dim(a, j * chunk, chunk)  # noqa: E731
        action = row(state.m_action)
        mtype = row(state.m_type)
        op = row(state.m_op)
        attr = row(state.m_attr)
        live = action != 0

        def anchor_gap(kind, anchor):
            # (J, S) unique-id match; masked max == match position, -1 if none
            idx = jnp.max(
                jnp.where(
                    (state.elem_id[None, :] == anchor[:, None]) & (pos[None, :] < n),
                    pos[None, :],
                    -1,
                ),
                axis=1,
            )
            elem_gap = jnp.where(kind == BK_BEFORE, 2 * idx, 2 * idx + 1)
            gap = jnp.where(
                kind == BK_START_OF_TEXT,
                jnp.int32(-1),
                jnp.where(kind == BK_END_OF_TEXT, big, elem_gap),
            )
            anchored = (kind == BK_START_OF_TEXT) | (kind == BK_END_OF_TEXT) | (idx >= 0)
            return gap, anchored

        s_gap, s_ok = anchor_gap(row(state.m_start_kind), row(state.m_start_elem))
        e_gap, e_ok = anchor_gap(row(state.m_end_kind), row(state.m_end_elem))

        cover = (
            live[:, None]
            & (s_gap[:, None] <= gap_before[None, :])
            & (gap_before[None, :] < e_gap[:, None])
            & (pos[None, :] < n)
        )  # (J, S)
        add_mask = cover & (action == MA_ADD)[:, None]
        rem_mask = cover & (action == MA_REMOVE)[:, None]
        op_col = op[:, None]

        # LWW types: reduce the chunk to per-slot maxima, combine into carry.
        add_rows, rem_rows = [], []
        link_attr = carry.link_attr
        for t in range(NUM_TYPES):
            if t == COMMENT_TYPE:
                add_rows.append(carry.add_op[t])
                rem_rows.append(carry.rem_op[t])
                continue
            tm = (mtype == t)[:, None]
            chunk_add = jnp.max(jnp.where(add_mask & tm, op_col, 0), axis=0)  # (S,)
            chunk_rem = jnp.max(jnp.where(rem_mask & tm, op_col, 0), axis=0)
            if t == LINK_TYPE:
                # max, not sum: a re-delivered mark row may appear twice in
                # the table (rows are appended without dedup), and both
                # copies carry the same attr.
                chunk_attr = jnp.max(
                    jnp.where(add_mask & tm & (op_col == chunk_add[None, :]),
                              attr[:, None], 0),
                    axis=0,
                )
                link_attr = jnp.where(
                    chunk_add > carry.add_op[t], chunk_attr, link_attr
                )
            add_rows.append(jnp.maximum(carry.add_op[t], chunk_add))
            rem_rows.append(jnp.maximum(carry.rem_op[t], chunk_rem))

        # Comments: per interned comment id, one vectorized segment-max over
        # the chunk axis — (J, C, S) masks reduce to (C, S) chunk maxima.
        is_comment = mtype == COMMENT_TYPE
        c_ids = jnp.arange(comment_capacity, dtype=jnp.int32)
        row_sel = is_comment[:, None] & (attr[:, None] == c_ids[None, :])  # (J, C)
        op3 = op[:, None, None]  # (J, 1, 1)
        chunk_c_add = jnp.max(
            jnp.where(row_sel[:, :, None] & add_mask[:, None, :], op3, 0), axis=0
        )
        chunk_c_rem = jnp.max(
            jnp.where(row_sel[:, :, None] & rem_mask[:, None, :], op3, 0), axis=0
        )
        c_add_op = jnp.maximum(carry.c_add_op, chunk_c_add)
        c_rem_op = jnp.maximum(carry.c_rem_op, chunk_c_rem)

        error = carry.error | jnp.any(live & ~(s_ok & e_ok))
        error = error | jnp.any(live & is_comment & (attr >= comment_capacity))
        return Carry(
            jnp.stack(add_rows), jnp.stack(rem_rows), link_attr,
            c_add_op, c_rem_op, error,
        )

    num_chunks = -(-m_cap // chunk)
    out = lax.fori_loop(0, num_chunks, body, carry)

    # Visibility: occupied and not tombstoned (one vectorized any-match).
    tombed = jnp.any(
        (state.elem_id[:, None] == state.tomb_id[None, :]) & (state.tomb_id != 0)[None, :],
        axis=1,
    )
    visible = (pos < n) & ~tombed

    lww_active = out.add_op > out.rem_op
    return ResolvedDocs(
        char=state.char,
        visible=visible,
        lww_active=lww_active,
        link_attr=jnp.where(lww_active[LINK_TYPE], out.link_attr, 0),
        comment_active=out.c_add_op > out.c_rem_op,
        overflow=state.overflow | out.error,
    )


def resolve(state: PackedDocs, comment_capacity: int = 32) -> ResolvedDocs:
    """Batched resolution over the doc axis."""
    return jax.vmap(lambda s: resolve_single(s, comment_capacity))(state)


resolve_jit = jax.jit(resolve, static_argnums=1)


def resolve_cursors(state: PackedDocs, visible, cursor_elem):
    """Batched stable-cursor resolution.

    Reference ``resolveCursor`` (src/micromerge.ts:868-870) returns
    ``findListElement(elemId).visible`` — the count of visible elements
    strictly before the cursor's element in metadata order, which collapses
    the cursor leftward when its anchor character has been deleted
    (src/micromerge.ts:1304-1328; tests test/micromerge.ts:1291-1418).

    ``cursor_elem`` is (D, C) packed element ids, 0 = padding; ``visible`` is
    the (D, S) visibility plane from :func:`resolve`.  Returns (D, C) int32
    visible indices, -1 for padding or element ids absent from the doc.
    """

    def one(elem_id, n, vis, cur):
        s_cap = elem_id.shape[0]
        pos = jnp.arange(s_cap, dtype=jnp.int32)
        match = (elem_id[None, :] == cur[:, None]) & (pos[None, :] < n)  # (C, S)
        found = jnp.any(match, axis=1)
        p = jnp.argmax(match, axis=1).astype(jnp.int32)
        before = jnp.sum(
            vis[None, :] & (pos[None, :] < p[:, None]), axis=1
        ).astype(jnp.int32)
        return jnp.where((cur != 0) & found, before, jnp.int32(-1))

    return jax.vmap(one)(state.elem_id, state.num_slots, visible, cursor_elem)


resolve_cursors_jit = jax.jit(resolve_cursors)


def cursor_width_bucket(needed: int) -> int:
    """Power-of-two cursor-axis width so varying cursor counts across calls
    reuse one compiled resolve_cursors program."""
    width = 4
    while width < needed:
        width *= 2
    return width


def pack_cursor_rows(cursor_map, num_docs: int, actor_table_for) -> "np.ndarray":
    """(D, W) packed cursor-element matrix for a per-doc cursor mapping.

    ``cursor_map``: {doc_index: [Cursor, ...]} with reference-shaped Cursor
    dicts; ``actor_table_for(doc_index)`` returns the doc's actor interner.
    Unknown actors / over-wide counters pack to 0 (= resolves to -1)."""
    import numpy as np

    from .packed import MAX_CTR, pack_id

    width = cursor_width_bucket(max([len(c) for c in cursor_map.values()] + [1]))
    rows = np.zeros((num_docs, width), np.int32)
    for d, cursors in cursor_map.items():
        actors = actor_table_for(d)
        if actors is None:
            continue
        for j, cur in enumerate(cursors):
            ctr, actor = cur["elemId"]
            idx = actors.get(actor)
            if idx is not None and ctr <= MAX_CTR:
                rows[d, j] = pack_id(ctr, idx)
    return rows


def oracle_cursor_positions(doc, cursors) -> list:
    """Scalar-replay cursor resolution with device semantics (-1 for absent
    elements) — the fallback-doc path shared by DocBatch and StreamingMerge."""
    from ..core.errors import IndexOutOfBounds, MissingObject

    out = []
    for cur in cursors:
        try:
            out.append(doc.resolve_cursor(cur))
        except (IndexOutOfBounds, MissingObject):
            out.append(-1)
    return out
