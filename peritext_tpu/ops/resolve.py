"""Read-time span resolution: mark table -> per-character mark state.

The convergent semantics (see ops/packed.py): a mark op covers character ``x``
iff, in the final element order, the op's start anchor position is <= the gap
just before ``x`` and its end anchor position is > that gap.  Anchor positions
live on the 2n+2 gap grid: ``before(e) -> 2*idx(e)``, ``after(e) ->
2*idx(e)+1``, ``startOfText -> -1``, ``endOfText -> +inf`` (reference
BoundaryPosition, src/micromerge.ts:266-270; this is the pure form of the
reference's materialized-gap walk :1002-1138).

Winner resolution per character follows core/spans.ops_to_marks: the governing
op per mark type is the max op id among covering ops (LWW for strong/em/link,
per-comment-id for comments).  Because max is associative, the mark table is
consumed in CHUNKS of ``MARK_CHUNK`` rows: each ``fori_loop`` iteration
reduces its chunk's covering ops to per-slot add/remove maxima and combines
them into the carried running maxima.  A character is marked iff its max
covering *add* op beats its max covering *remove* op — so no winner-action or
winner-id bookkeeping is carried at all, which (together with chunking)
cuts the loop-carried HBM traffic by more than an order of magnitude versus
a per-mark walk.  Padding chunk reads may overlap the previous chunk
(dynamic_slice clamps); that is harmless because max/or updates are
idempotent.

Comments are the expensive plane: per interned comment id a winner val per
slot is needed, i.e. a (C, S) plane per document.  Three measures keep that
off the critical path: (a) the "val trick" — the carried state per key is the
single uint32 ``(op_id << 1) | is_add`` maximum, whose low bit is the
add/remove verdict, halving both carries and reductions; (b) the per-chunk
per-id reduction is PLATFORM-ADAPTIVE (:func:`comment_reduce_impl`): on TPU
a dense (J, C, S) masked max that XLA fuses into plain reductions (measured
faster there than a segment_max scatter, which serializes), elsewhere a
batched scatter-max over the comment-id axis — the dense product is O(JxCxS)
of mostly-masked work and measured ~150x slower than the scatter on XLA CPU
(93 ms vs sub-ms on the 64-doc smoke block), which made the with-comments
resolve the whole smoke digest cost; the two forms are bit-identical (max
over the same masked values, out-of-range ids dropped both ways); (c)
resolution is compiled with a static ``with_comments`` flag, and the paths
that never read comment state (convergence digests, cursor resolution,
overflow counting) compile with it off, so the comment work vanishes from
those programs entirely.  The output plane is bit-packed (``comment_bits``),
shrinking the device->host read transfer 32x.

Visibility is also computed here: a slot is visible iff occupied and its
element id is absent from the tombstone table (one vectorized any-match).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..schema import ALL_MARKS, MARK_INDEX
from .packed import (
    BK_BEFORE,
    BK_END_OF_TEXT,
    BK_START_OF_TEXT,
    MA_ADD,
    MA_REMOVE,
    PackedDocs,
)

NUM_TYPES = len(ALL_MARKS)
COMMENT_TYPE = MARK_INDEX["comment"]
LINK_TYPE = MARK_INDEX["link"]
#: chunk width of the mark-table loop: wide enough that common tables (<= 128
#: rows) resolve in a single carry-free pass; long-doc tables loop with
#: (C, S) carries only between chunks.
MARK_CHUNK = 128


def comment_reduce_impl() -> str:
    """Per-chunk comment-winner reduction implementation: ``"dense"`` (the
    (J, C, S) masked max — fuses into plain reductions on TPU, where
    scatters serialize) or ``"scatter"`` (a batched scatter-max over the
    comment-id axis — O(JxS) work, ~150x faster on XLA CPU).  Read at TRACE
    time from the default backend: both forms lower everywhere and are
    bit-identical, so a mixed-platform process (TPU plugin registered, CPU
    mesh computing) merely picks a slower-but-correct form — the same
    posture as :func:`..kernel.resolve_insert_impl`, minus the correctness
    stakes that force that one to the jit boundary."""
    return "dense" if jax.default_backend() == "tpu" else "scatter"


class ResolvedDocs(NamedTuple):
    """Per-character resolved formatting for a batch of docs."""

    char: jnp.ndarray  # int32 (D, S)
    visible: jnp.ndarray  # bool (D, S)
    #: (D, T, S): winning op is an addMark, per LWW mark type T
    lww_active: jnp.ndarray
    #: (D, S): interned url of the winning link op (0 = none)
    link_attr: jnp.ndarray
    #: (D, W, S) uint32 bitmask: bit ``c % 32`` of word ``c // 32`` set iff
    #: interned comment id ``c``'s winning op is an addMark (W = ceil(C/32);
    #: packed so the host transfer is 32x smaller than a bool plane)
    comment_bits: jnp.ndarray
    overflow: jnp.ndarray  # bool (D,)


def resolve_single(
    state: PackedDocs, comment_capacity: int, with_comments: bool = True
) -> ResolvedDocs:
    """Resolve one document (unbatched arrays).

    ``with_comments=False`` compiles the comment planes away entirely (the
    returned ``comment_bits`` has zero words); the comment-attr overflow
    *check* still runs so ``overflow`` semantics are identical."""
    s_cap = state.elem_id.shape[0]
    m_cap = state.m_action.shape[0]
    pos = jnp.arange(s_cap, dtype=jnp.int32)
    n = state.num_slots
    big = jnp.int32(2 * s_cap + 1)
    gap_before = 2 * pos  # the gap governing each slot's character
    c_cap = comment_capacity if with_comments else 0
    c_words = -(-c_cap // 32) if with_comments else 0

    # The "val trick": winner state per (key, slot) is the single uint32
    # ``(op_id << 1) | is_add`` maximum over covering rows — op ids are unique
    # (re-delivered duplicate rows tie with identical action), so the winner's
    # low bit IS the add/remove verdict.  One max instead of separate
    # add-maximum and remove-maximum: half the carries, half the reductions.
    class Carry(NamedTuple):
        lww_val: jnp.ndarray  # (T, S) uint32 max (op<<1|is_add) per LWW type
        link_attr: jnp.ndarray  # (S,) attr of the current link winner
        c_val: jnp.ndarray  # (C, S) uint32 per interned comment id
        error: jnp.ndarray  # () bool

    carry = Carry(
        lww_val=jnp.zeros((NUM_TYPES, s_cap), jnp.uint32),
        link_attr=jnp.zeros((s_cap,), jnp.int32),
        c_val=jnp.zeros((c_cap, s_cap), jnp.uint32),
        error=jnp.asarray(False),
    )

    chunk = max(1, min(MARK_CHUNK, m_cap))

    def body(j, carry: Carry) -> Carry:
        row = lambda a: lax.dynamic_slice_in_dim(a, j * chunk, chunk)  # noqa: E731
        action = row(state.m_action)
        mtype = row(state.m_type)
        op = row(state.m_op)
        attr = row(state.m_attr)
        live = action != 0

        def anchor_gap(kind, anchor):
            # (J, S) unique-id match; masked max == match position, -1 if none
            idx = jnp.max(
                jnp.where(
                    (state.elem_id[None, :] == anchor[:, None]) & (pos[None, :] < n),
                    pos[None, :],
                    -1,
                ),
                axis=1,
            )
            elem_gap = jnp.where(kind == BK_BEFORE, 2 * idx, 2 * idx + 1)
            gap = jnp.where(
                kind == BK_START_OF_TEXT,
                jnp.int32(-1),
                jnp.where(kind == BK_END_OF_TEXT, big, elem_gap),
            )
            anchored = (kind == BK_START_OF_TEXT) | (kind == BK_END_OF_TEXT) | (idx >= 0)
            return gap, anchored

        s_gap, s_ok = anchor_gap(row(state.m_start_kind), row(state.m_start_elem))
        e_gap, e_ok = anchor_gap(row(state.m_end_kind), row(state.m_end_elem))

        cover = (
            live[:, None]
            & (s_gap[:, None] <= gap_before[None, :])
            & (gap_before[None, :] < e_gap[:, None])
            & (pos[None, :] < n)
        )  # (J, S)
        val = (op.astype(jnp.uint32) << 1) | (action == MA_ADD)  # (J,)
        val_col = val[:, None]

        # LWW types: reduce the chunk to per-slot winner vals, combine.
        val_rows = []
        link_attr = carry.link_attr
        is_comment = mtype == COMMENT_TYPE
        for t in range(NUM_TYPES):
            if t == COMMENT_TYPE:
                val_rows.append(carry.lww_val[t])
                continue
            sel = cover & (mtype == t)[:, None]  # (J, S)
            chunk_val = jnp.max(jnp.where(sel, val_col, 0), axis=0)  # (S,)
            if t == LINK_TYPE:
                # attr of the chunk winner (max, not sum: duplicate rows tie
                # with equal attrs); gated on add at the output, so a remove
                # winner's attr is harmless.
                chunk_attr = jnp.max(
                    jnp.where(sel & (val_col == chunk_val[None, :]),
                              attr[:, None], 0),
                    axis=0,
                )
                link_attr = jnp.where(
                    chunk_val > carry.lww_val[t], chunk_attr, link_attr
                )
            val_rows.append(jnp.maximum(carry.lww_val[t], chunk_val))

        # Comments: per interned comment id, the winner-val max over the
        # chunk's covering comment rows — dense (J, C, S) masked max on TPU,
        # batched scatter-max elsewhere (see comment_reduce_impl; the two
        # are bit-identical, and a non-comment or out-of-range row
        # contributes 0 / drops under both forms).
        if with_comments:
            data = jnp.where(cover & is_comment[:, None], val_col, 0)  # (J, S)
            if comment_reduce_impl() == "dense":
                sel_c = (
                    attr[:, None]
                    == jnp.arange(comment_capacity, dtype=jnp.int32)[None, :]
                )  # (J, C)
                chunk_c = jnp.max(
                    jnp.where(sel_c[:, :, None], data[:, None, :], 0), axis=0
                )  # (C, S)
            else:
                chunk_c = (
                    jnp.zeros((comment_capacity, s_cap), jnp.uint32)
                    .at[attr]
                    .max(data, mode="drop")
                )
            c_val = jnp.maximum(carry.c_val, chunk_c)
        else:
            c_val = carry.c_val

        error = carry.error | jnp.any(live & ~(s_ok & e_ok))
        error = error | jnp.any(live & is_comment & (attr >= comment_capacity))
        return Carry(jnp.stack(val_rows), link_attr, c_val, error)

    num_chunks = -(-m_cap // chunk)
    out = lax.fori_loop(0, num_chunks, body, carry)

    # Visibility: occupied and not tombstoned (one vectorized any-match).
    tombed = jnp.any(
        (state.elem_id[:, None] == state.tomb_id[None, :]) & (state.tomb_id != 0)[None, :],
        axis=1,
    )
    visible = (pos < n) & ~tombed

    lww_active = (out.lww_val & 1) == 1
    if with_comments:
        # pack per-id verdicts into uint32 words: (C, S) -> (W, S)
        active = (out.c_val & 1).astype(jnp.uint32)  # (C, S)
        padded = jnp.zeros((c_words * 32, s_cap), jnp.uint32).at[:c_cap].set(active)
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :, None]
        comment_bits = jnp.sum(
            padded.reshape(c_words, 32, s_cap) * weights, axis=1, dtype=jnp.uint32
        )
    else:
        comment_bits = jnp.zeros((0, s_cap), jnp.uint32)
    return ResolvedDocs(
        char=state.char,
        visible=visible,
        lww_active=lww_active,
        link_attr=jnp.where(lww_active[LINK_TYPE], out.link_attr, 0),
        comment_bits=comment_bits,
        overflow=state.overflow | out.error,
    )


def resolve(
    state: PackedDocs, comment_capacity: int = 32, with_comments: bool = True
) -> ResolvedDocs:
    """Batched resolution over the doc axis."""
    return jax.vmap(
        lambda s: resolve_single(s, comment_capacity, with_comments)
    )(state)


resolve_jit = jax.jit(resolve, static_argnums=(1, 2))


def resolve_cursors(state: PackedDocs, visible, cursor_elem):
    """Batched stable-cursor resolution.

    Reference ``resolveCursor`` (src/micromerge.ts:868-870) returns
    ``findListElement(elemId).visible`` — the count of visible elements
    strictly before the cursor's element in metadata order, which collapses
    the cursor leftward when its anchor character has been deleted
    (src/micromerge.ts:1304-1328; tests test/micromerge.ts:1291-1418).

    ``cursor_elem`` is (D, C) packed element ids, 0 = padding; ``visible`` is
    the (D, S) visibility plane from :func:`resolve`.  Returns (D, C) int32
    visible indices, -1 for padding or element ids absent from the doc.
    """

    def one(elem_id, n, vis, cur):
        s_cap = elem_id.shape[0]
        pos = jnp.arange(s_cap, dtype=jnp.int32)
        match = (elem_id[None, :] == cur[:, None]) & (pos[None, :] < n)  # (C, S)
        found = jnp.any(match, axis=1)
        p = jnp.argmax(match, axis=1).astype(jnp.int32)
        before = jnp.sum(
            vis[None, :] & (pos[None, :] < p[:, None]), axis=1
        ).astype(jnp.int32)
        return jnp.where((cur != 0) & found, before, jnp.int32(-1))

    return jax.vmap(one)(state.elem_id, state.num_slots, visible, cursor_elem)


resolve_cursors_jit = jax.jit(resolve_cursors)


def cursor_width_bucket(needed: int) -> int:
    """Power-of-two cursor-axis width so varying cursor counts across calls
    reuse one compiled resolve_cursors program (canonical spelling:
    utils/shapes.next_pow2, floor 4)."""
    from ..utils.shapes import next_pow2

    return next_pow2(needed, floor=4)


def pack_cursor_rows(cursor_map, num_docs: int, actor_table_for) -> "np.ndarray":
    """(D, W) packed cursor-element matrix for a per-doc cursor mapping.

    ``cursor_map``: {doc_index: [Cursor, ...]} with reference-shaped Cursor
    dicts; ``actor_table_for(doc_index)`` returns the doc's actor interner.
    Unknown actors / over-wide counters pack to 0 (= resolves to -1)."""
    import numpy as np

    from .packed import MAX_CTR, pack_id

    width = cursor_width_bucket(max([len(c) for c in cursor_map.values()] + [1]))
    rows = np.zeros((num_docs, width), np.int32)
    for d, cursors in cursor_map.items():
        actors = actor_table_for(d)
        if actors is None:
            continue
        for j, cur in enumerate(cursors):
            ctr, actor = cur["elemId"]
            idx = actors.get(actor)
            if idx is not None and ctr <= MAX_CTR:
                rows[d, j] = pack_id(ctr, idx)
    return rows


def oracle_cursor_positions(doc, cursors) -> list:
    """Scalar-replay cursor resolution with device semantics (-1 for absent
    elements) — the fallback-doc path shared by DocBatch and StreamingMerge."""
    from ..core.errors import IndexOutOfBounds, MissingObject

    out = []
    for cur in cursors:
        try:
            out.append(doc.resolve_cursor(cur))
        except (IndexOutOfBounds, MissingObject):
            out.append(-1)
    return out
