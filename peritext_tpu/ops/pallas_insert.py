"""Pallas TPU kernel for the sequential RGA insert phase.

This is the hot loop of the whole framework (kernel.py phase 1, reference
``applyListInsert`` src/micromerge.ts:1187-1245).  The plain-XLA formulation
(`kernel._insert_loop` under vmap) carries the full ``(D, S)`` element-id and
character tensors through HBM on every one of the K insert steps; at the
BASELINE config-4 scale that is ~K x 4 x D x S bytes of traffic and the loop
is purely bandwidth bound.

The Pallas kernel instead blocks the doc axis onto the grid and keeps each
block's entire document state resident in VMEM across the WHOLE K-step loop:
HBM traffic drops from O(K * D * S) to O(D * (S + K)) — read the state and
the op streams once, write the state once.

Layout: everything is transposed so **documents ride the 128-wide lane
axis** and slots/ops ride sublanes.  That makes the per-step stream access a
dynamic *sublane* slice (cheap on TPU; dynamic lane indexing would force a
relayout every iteration), reductions over slots are sublane reductions, and
the RGA splice is a sublane rotate.  ``argmax`` is avoided (unsupported for
int32 in mosaic): the reference-element position comes from a masked integer
max, which is exact because element ids are unique so at most one slot
matches.

Semantics are identical to ``kernel._insert_loop`` (the CPU/differential
path); tests assert equality between the two in interpreter mode and
``kernel.apply_batch`` selects this kernel automatically on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either so the
# kernel builds against both sides of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

LANES = 128


def _insert_body(ins_ref, ins_op, ins_char, pos, s_cap):
    """The per-insert step shared by the single-chunk and chunked kernels.

    Mask algebra exploits two invariants to keep per-step VPU work minimal:
    real element ids are never 0, and empty slots hold id 0.  So the
    reference match needs no ``pos < n`` guard (a non-HEAD ref can't match a
    padding slot), and the convergence skip needs none either — the first
    padding slot (id 0 < any op id) acts as a natural sentinel at exactly
    ``pos == n``, which is the append position.  The no-op case folds into
    the splice select by forcing the insert position to S (never matched by
    ``pos``), so the carry needs no final where.
    """

    def body(k, carry):
        elem, chars, n, ov = carry  # (S,L) (S,L) (1,L) (1,L)
        ref = ins_ref[pl.ds(k, 1), :]  # (1,L)
        op = ins_op[pl.ds(k, 1), :]
        ch = ins_char[pl.ds(k, 1), :]
        live = op != 0
        is_head = ref == 0

        # Locate the reference element.  Ids are unique, so the masked max
        # IS the match position; no match (or HEAD) yields -1.
        p = jnp.max(jnp.where(elem == ref, pos, -1), axis=0, keepdims=True)
        found = is_head | (p >= 0)
        p = jnp.where(is_head, jnp.int32(-1), p)
        ok = live & found & (n < s_cap)

        # Convergence skip (reference :1201-1208): first position right of
        # the reference whose element id is NOT greater than the new op id.
        q = jnp.min(
            jnp.where((pos > p) & (elem < op), pos, s_cap), axis=0, keepdims=True
        )
        q = jnp.where(ok, q, s_cap)  # no-op => splice position out of range

        lt, eq = pos < q, pos == q
        new_elem = jnp.where(lt, elem, jnp.where(eq, op, jnp.roll(elem, 1, axis=0)))
        new_char = jnp.where(lt, chars, jnp.where(eq, ch, jnp.roll(chars, 1, axis=0)))
        return (
            new_elem,
            new_char,
            n + ok.astype(jnp.int32),
            ov | ((live & ~found) | (live & (n >= s_cap))).astype(jnp.int32),
        )

    return body


def _insert_kernel(ins_ref, ins_op, ins_char, elem_in, char_in, n_in, ov_in,
                   elem_out, char_out, n_out, ov_out):
    """One grid cell: ALL K inserts for an (S, L) block of documents (the
    fast path when the whole op stream fits VMEM next to the state)."""
    s_cap, lanes = elem_in.shape
    pos = lax.broadcasted_iota(jnp.int32, (s_cap, lanes), 0)
    body = _insert_body(ins_ref, ins_op, ins_char, pos, s_cap)
    init = (elem_in[:], char_in[:], n_in[:], ov_in[:])
    elem, chars, n, ov = lax.fori_loop(0, ins_ref.shape[0], body, init)
    elem_out[:] = elem
    char_out[:] = chars
    n_out[:] = n
    ov_out[:] = ov


def _insert_kernel_chunked(ins_ref, ins_op, ins_char, elem_in, char_in, n_in,
                           ov_in, elem_out, char_out, n_out, ov_out):
    """One grid cell: one op-stream CHUNK of inserts for an (S, L) block of
    documents.  The grid is (doc blocks, stream chunks) with the stream axis
    sequential ("arbitrary"): the state OUTPUT blocks are indexed by doc
    only, so Pallas keeps them resident in VMEM across all chunk steps —
    chunk 0 seeds them from the inputs, later chunks continue in place.
    Chunking bounds VMEM by the chunk width instead of the whole K stream
    (BASELINE config-4 long docs overflow VMEM otherwise)."""
    s_cap, lanes = elem_in.shape
    pos = lax.broadcasted_iota(jnp.int32, (s_cap, lanes), 0)

    @pl.when(pl.program_id(1) == 0)
    def _seed():
        elem_out[:] = elem_in[:]
        char_out[:] = char_in[:]
        n_out[:] = n_in[:]
        ov_out[:] = ov_in[:]

    body = _insert_body(ins_ref, ins_op, ins_char, pos, s_cap)
    init = (elem_out[:], char_out[:], n_out[:], ov_out[:])
    elem, chars, n, ov = lax.fori_loop(0, ins_ref.shape[0], body, init)
    elem_out[:] = elem
    char_out[:] = chars
    n_out[:] = n
    ov_out[:] = ov


@functools.partial(jax.jit, static_argnames=("interpret", "loop_slots"))
def insert_batch_pallas(elem_id, char, num_slots, overflow,
                        ins_ref, ins_op, ins_char, *, interpret: bool = False,
                        loop_slots: int | None = None):
    """Pallas-accelerated equivalent of ``vmap(kernel._insert_loop)``.

    Args mirror the lax path: (D,S) elem_id/char, (D,) num_slots, (D,) bool
    overflow, (D,K) insert streams.  Returns the same tuple of updated
    arrays.  The doc axis is padded up to a multiple of 128 lanes (padded
    docs carry op id 0 == not live, so they are untouched no-ops).

    ``loop_slots``: static upper bound on ``max(num_slots) + live inserts``
    known by the caller (e.g. K for a batch built from empty docs).  The
    K-step loop then runs on only the first ``loop_slots`` slot rows — the
    splice can never move an element across that boundary when the bound
    holds — roughly halving VPU work for fresh batches.  If the bound is
    violated the kernel flags ``overflow`` (the API's scalar-fallback path),
    so a bad bound degrades performance, never correctness.
    """
    d, s_cap = elem_id.shape
    k = ins_ref.shape[1]
    if k == 0:  # mark/delete-only batch: the insert phase is a no-op
        return elem_id, char, num_slots, overflow
    s_loop = effective_loop_slots(s_cap, loop_slots)
    kc = _stream_chunk(s_loop, k)
    kp = -(-k // kc) * kc  # stream padded to whole chunks (op id 0 = no-op)
    dp = -(-d // LANES) * LANES
    pad = dp - d
    chunked = kp != kc  # stream larger than one VMEM-resident chunk

    def t(x, extra_rows=0):  # (D, W) -> (W + extra, Dp)
        return jnp.pad(x.T.astype(jnp.int32), ((0, extra_rows), (0, pad)))

    if chunked:
        grid = (dp // LANES, kp // kc)
        index = lambda i, j: (0, i)  # noqa: E731
        stream_index = lambda i, j: (j, i)  # noqa: E731
        kernel = _insert_kernel_chunked
        params = dict(
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "arbitrary"),
                vmem_limit_bytes=_VMEM_LIMIT,
            )
        )
    else:
        grid = (dp // LANES,)
        index = lambda i: (0, i)  # noqa: E731
        stream_index = index
        kernel = _insert_kernel
        params = dict(
            compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT)
        )

    state_col = lambda width: pl.BlockSpec(  # noqa: E731
        (width, LANES), index, memory_space=pltpu.VMEM
    )
    stream_col = pl.BlockSpec((kc, LANES), stream_index, memory_space=pltpu.VMEM)

    elem, chars, n, ov = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            stream_col, stream_col, stream_col,
            state_col(s_loop), state_col(s_loop), state_col(1), state_col(1),
        ],
        out_specs=[state_col(s_loop), state_col(s_loop), state_col(1), state_col(1)],
        out_shape=[
            jax.ShapeDtypeStruct((s_loop, dp), jnp.int32),
            jax.ShapeDtypeStruct((s_loop, dp), jnp.int32),
            jax.ShapeDtypeStruct((1, dp), jnp.int32),
            jax.ShapeDtypeStruct((1, dp), jnp.int32),
        ],
        interpret=interpret,
        **params,
    )(
        t(ins_ref, kp - k), t(ins_op, kp - k), t(ins_char, kp - k),
        t(elem_id[:, :s_loop]), t(char[:, :s_loop]),
        t(num_slots.reshape(d, 1)), t(overflow.reshape(d, 1)),
    )

    elem_new, char_new = elem[:, :d].T, chars[:, :d].T
    if s_loop < s_cap:
        # Slots past the loop window are untouched by construction.
        elem_new = jnp.concatenate([elem_new, elem_id[:, s_loop:]], axis=1)
        char_new = jnp.concatenate([char_new, char[:, s_loop:]], axis=1)
    return elem_new, char_new, n[0, :d], ov[0, :d] != 0


#: VMEM ceiling requested from the compiler (v5e has 128M per core; the
#: default scoped limit is only 16M) and the occupancy budget this module
#: plans against.  The budget leaves a wide margin under the ceiling.
_VMEM_LIMIT = 100 * 1024 * 1024
_VMEM_BUDGET = 72 * 1024 * 1024


def effective_loop_slots(s_cap: int, loop_slots: int | None) -> int:
    """The slot-window height the kernel will actually use."""
    if loop_slots is None:
        return s_cap
    return max(8, min(-(-loop_slots // 8) * 8, s_cap))


def _state_bytes(s_loop: int) -> int:
    """Resident bytes attributable to the (elem, char) state at one grid
    cell, counted conservatively at 6 copies of the 2-array state (pipeline
    double-buffered inputs, revisited outputs, fori_loop carry, and the
    chunk-0 seed copy — observed occupancy on v5e is ~6x)."""
    return 6 * (2 * s_loop * LANES * 4)


def _stream_bytes(kc: int) -> int:
    """Resident bytes for the 3 op-stream blocks (double-buffered inputs)."""
    return 2 * (3 * kc * LANES * 4)


def _stream_chunk(s_loop: int, k: int) -> int:
    """Op-stream chunk width: the whole stream when it fits the VMEM budget
    next to the resident state blocks (the fast single-chunk kernel, no
    padding); otherwise the largest multiple-of-8 chunk that fits."""
    room = max(_VMEM_BUDGET - _state_bytes(s_loop), 0)
    kc = room // (2 * 3 * LANES * 4)
    if kc >= k:
        return k
    return max(8, (kc // 8) * 8)


def pallas_vmem_ok(s_loop: int) -> bool:
    """Whether the kernel's resident state for this slot window fits VMEM at
    all (the op-stream width never matters: chunking bounds it to whatever
    room remains, down to the minimum chunk of 8).  When False, callers
    should use the lax path, which streams state through HBM and has no
    such limit."""
    return _state_bytes(s_loop) + _stream_bytes(8) <= _VMEM_BUDGET
