"""Pallas TPU kernel for the sequential RGA insert phase.

This is the hot loop of the whole framework (kernel.py phase 1, reference
``applyListInsert`` src/micromerge.ts:1187-1245).  The plain-XLA formulation
(`kernel._insert_loop` under vmap) carries the full ``(D, S)`` element-id and
character tensors through HBM on every one of the K insert steps; at the
BASELINE config-4 scale that is ~K x 4 x D x S bytes of traffic and the loop
is purely bandwidth bound.

The Pallas kernel instead blocks the doc axis onto the grid and keeps each
block's entire document state resident in VMEM across the WHOLE K-step loop:
HBM traffic drops from O(K * D * S) to O(D * (S + K)) — read the state and
the op streams once, write the state once.

Layout: everything is transposed so **documents ride the 128-wide lane
axis** and slots/ops ride sublanes.  That makes the per-step stream access a
dynamic *sublane* slice (cheap on TPU; dynamic lane indexing would force a
relayout every iteration), reductions over slots are sublane reductions, and
the RGA splice is a sublane rotate.  ``argmax`` is avoided (unsupported for
int32 in mosaic): the reference-element position comes from a masked integer
max, which is exact because element ids are unique so at most one slot
matches.

Semantics are identical to ``kernel._insert_loop`` (the CPU/differential
path); tests assert equality between the two in interpreter mode and
``kernel.apply_batch`` selects this kernel automatically on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128


def _insert_kernel(ins_ref, ins_op, ins_char, elem_in, char_in, n_in, ov_in,
                   elem_out, char_out, n_out, ov_out):
    """One grid cell: all K inserts for an (S, L) block of documents.

    Mask algebra exploits two invariants to keep per-step VPU work minimal:
    real element ids are never 0, and empty slots hold id 0.  So the
    reference match needs no ``pos < n`` guard (a non-HEAD ref can't match a
    padding slot), and the convergence skip needs none either — the first
    padding slot (id 0 < any op id) acts as a natural sentinel at exactly
    ``pos == n``, which is the append position.  The no-op case folds into
    the splice select by forcing the insert position to S (never matched by
    ``pos``), so the carry needs no final where.
    """
    s_cap, lanes = elem_in.shape
    k_total = ins_ref.shape[0]
    pos = lax.broadcasted_iota(jnp.int32, (s_cap, lanes), 0)

    def body(k, carry):
        elem, chars, n, ov = carry  # (S,L) (S,L) (1,L) (1,L)
        ref = ins_ref[pl.ds(k, 1), :]  # (1,L)
        op = ins_op[pl.ds(k, 1), :]
        ch = ins_char[pl.ds(k, 1), :]
        live = op != 0
        is_head = ref == 0

        # Locate the reference element.  Ids are unique, so the masked max
        # IS the match position; no match (or HEAD) yields -1.
        p = jnp.max(jnp.where(elem == ref, pos, -1), axis=0, keepdims=True)
        found = is_head | (p >= 0)
        p = jnp.where(is_head, jnp.int32(-1), p)
        ok = live & found & (n < s_cap)

        # Convergence skip (reference :1201-1208): first position right of
        # the reference whose element id is NOT greater than the new op id.
        q = jnp.min(
            jnp.where((pos > p) & (elem < op), pos, s_cap), axis=0, keepdims=True
        )
        q = jnp.where(ok, q, s_cap)  # no-op => splice position out of range

        lt, eq = pos < q, pos == q
        new_elem = jnp.where(lt, elem, jnp.where(eq, op, jnp.roll(elem, 1, axis=0)))
        new_char = jnp.where(lt, chars, jnp.where(eq, ch, jnp.roll(chars, 1, axis=0)))
        return (
            new_elem,
            new_char,
            n + ok.astype(jnp.int32),
            ov | ((live & ~found) | (live & (n >= s_cap))).astype(jnp.int32),
        )

    init = (elem_in[:], char_in[:], n_in[:], ov_in[:])
    elem, chars, n, ov = lax.fori_loop(0, k_total, body, init)
    elem_out[:] = elem
    char_out[:] = chars
    n_out[:] = n
    ov_out[:] = ov


@functools.partial(jax.jit, static_argnames=("interpret", "loop_slots"))
def insert_batch_pallas(elem_id, char, num_slots, overflow,
                        ins_ref, ins_op, ins_char, *, interpret: bool = False,
                        loop_slots: int | None = None):
    """Pallas-accelerated equivalent of ``vmap(kernel._insert_loop)``.

    Args mirror the lax path: (D,S) elem_id/char, (D,) num_slots, (D,) bool
    overflow, (D,K) insert streams.  Returns the same tuple of updated
    arrays.  The doc axis is padded up to a multiple of 128 lanes (padded
    docs carry op id 0 == not live, so they are untouched no-ops).

    ``loop_slots``: static upper bound on ``max(num_slots) + live inserts``
    known by the caller (e.g. K for a batch built from empty docs).  The
    K-step loop then runs on only the first ``loop_slots`` slot rows — the
    splice can never move an element across that boundary when the bound
    holds — roughly halving VPU work for fresh batches.  If the bound is
    violated the kernel flags ``overflow`` (the API's scalar-fallback path),
    so a bad bound degrades performance, never correctness.
    """
    d, s_cap = elem_id.shape
    k = ins_ref.shape[1]
    s_loop = s_cap if loop_slots is None else max(8, min(-(-loop_slots // 8) * 8, s_cap))
    dp = -(-d // LANES) * LANES
    pad = dp - d

    def t(x):  # (D, W) -> (W, Dp)
        return jnp.pad(x.T.astype(jnp.int32), ((0, 0), (0, pad)))

    col = lambda width: pl.BlockSpec(  # noqa: E731
        (width, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    elem, chars, n, ov = pl.pallas_call(
        _insert_kernel,
        grid=(dp // LANES,),
        in_specs=[
            col(k), col(k), col(k),
            col(s_loop), col(s_loop), col(1), col(1),
        ],
        out_specs=[col(s_loop), col(s_loop), col(1), col(1)],
        out_shape=[
            jax.ShapeDtypeStruct((s_loop, dp), jnp.int32),
            jax.ShapeDtypeStruct((s_loop, dp), jnp.int32),
            jax.ShapeDtypeStruct((1, dp), jnp.int32),
            jax.ShapeDtypeStruct((1, dp), jnp.int32),
        ],
        interpret=interpret,
    )(
        t(ins_ref), t(ins_op), t(ins_char),
        t(elem_id[:, :s_loop]), t(char[:, :s_loop]),
        t(num_slots.reshape(d, 1)), t(overflow.reshape(d, 1)),
    )

    elem_new, char_new = elem[:, :d].T, chars[:, :d].T
    if s_loop < s_cap:
        # Slots past the loop window are untouched by construction.
        elem_new = jnp.concatenate([elem_new, elem_id[:, s_loop:]], axis=1)
        char_new = jnp.concatenate([char_new, char[:, s_loop:]], axis=1)
    return elem_new, char_new, n[0, :d], ov[0, :d] != 0
