"""Ragged paged apply: one compiled program over the whole page pool.

Every other device path buckets — the padded apply pads all docs to the
slot capacity, the paged apply groups docs by power-of-two page count and
pads each group's row axis, and both pay a log2 compile ladder plus padded
FLOPs for the privilege.  This module is the Ragged Paged Attention answer
(PAPERS.md): the causal-insert round runs DIRECTLY against the ``(N, P)``
page pool, consuming the per-doc page tables ragged — true op counts and
true page counts arrive as *data* (plan planes + traced loop bounds), so
the compiled shape depends only on the pool size and the round's stream
staging widths.  A mixed drain of tweets, essays and book-scale docs is
ONE executable (tests/test_recompile_sentinel.py pins it), and padded
slots cost zero loop trips.

Two implementations behind ``resolve_ragged_impl`` (ops/kernel.py):

* ``"lax"`` — the pool-walk fallback every CPU path runs (tier-1, smoke
  ladders).  Per insert step it operates on the whole ``(N, P)`` pool at
  once: per-doc reductions become segment reductions over the ``owner``
  plane (``.at[owner].min/max``), and the RGA splice's roll becomes a
  lane shift whose lane-0 value comes through ``prev_page``.  One
  ``lax.fori_loop`` with a TRACED bound = the round's max true insert
  count; deletes build their target-exists matrix the same way.
* ``"pallas"`` / ``"pallas_interpret"`` — the TPU kernel: grid over docs
  with the page table scalar-prefetched, each doc's true pages gathered
  once into a VMEM window, its true ops applied, pages written back
  (``input_output_aliases`` keeps the pool in place).  The per-doc window
  ``(max_doc_pages, P)`` is deliberately the unit the v5e-8 mesh roadmap
  item will shard.

Byte-equality with the padded oracle holds phase by phase: the insert math
is kernel._insert_loop with positions relabeled through ``pos_base``
(element ids are unique, so the segment min over matches IS the padded
argmax), the delete/mark/register phases ARE kernel._post_insert_doc /
_apply_map_doc vmapped over the dense aux rows, with the target-exists
mask precomputed against pool pages.  tests/test_ragged.py pins the
equality across every workload family.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import GLOBAL_DEVPROF, note_jit_dispatch as _note_dispatch
from .kernel import (
    PAGED_AUX_FIELDS,
    _apply_map_doc,
    _post_insert_doc,
    resolve_ragged_impl,
    resolve_state_donation,
)
from .packed import PackedDocs

#: sentinel "no position" for the segment-min reductions (any real slot
#: position is far below it; int32 max would overflow the +1 in minimum)
_INF = 2**30

_NUM_SLOTS = PAGED_AUX_FIELDS.index("num_slots")
_OVERFLOW = PAGED_AUX_FIELDS.index("overflow")


def _pad_row(x):
    """Append one all-zero row — the inert segment every unowned pool page
    (owner == num_rows) reduces into and gathers from."""
    return jnp.concatenate(
        [x, jnp.zeros((1,) + x.shape[1:], x.dtype)], axis=0
    )


def _ragged_insert_lax(pool_elem, pool_char, owner, pos_base, prev_page,
                       n0, ov0, cap, ins_ref, ins_op, ins_char, k_ins):
    """Pool-walk insert phase: kernel._insert_loop over the whole pool.

    All per-doc operands carry one trailing inert row (index B = the owner
    sentinel); ``cap`` is each doc's TRUE allocated slot coverage
    (page_count * P) — by the ensure_rows discipline it covers every
    admitted insert up to the slot capacity, so the overflow point is the
    padded oracle's."""
    p = pool_elem.shape[1]
    bp1 = n0.shape[0]
    lane = jnp.arange(p, dtype=jnp.int32)
    pos = pos_base[:, None] + lane[None, :]  # (N, P) global slot positions

    def body(k, carry):
        elem, chars, n, ov = carry
        ref = lax.dynamic_index_in_dim(ins_ref, k, axis=1, keepdims=False)
        op = lax.dynamic_index_in_dim(ins_op, k, axis=1, keepdims=False)
        ch = lax.dynamic_index_in_dim(ins_char, k, axis=1, keepdims=False)
        live = op != 0
        is_head = ref == 0
        n_pg = n[owner]  # (N,) owner doc's current count, per page
        # reference match: ids are unique, so the segment MIN over matching
        # positions is exactly the padded path's argmax(match)
        match = (elem == ref[owner][:, None]) & (pos < n_pg[:, None])
        page_min = jnp.min(jnp.where(match, pos, _INF), axis=1)
        pmin = jnp.full((bp1,), _INF, jnp.int32).at[owner].min(page_min)
        found = is_head | (pmin < _INF)
        pref = jnp.where(is_head, jnp.int32(-1), pmin)
        ok = live & found & (n < cap)
        # convergence skip: first position right of the reference whose
        # element id is NOT greater than the inserting op's id
        candidate = (
            (pos > pref[owner][:, None])
            & (pos < n_pg[:, None])
            & (elem < op[owner][:, None])
        )
        page_q = jnp.min(jnp.where(candidate, pos, _INF), axis=1)
        q = jnp.minimum(
            jnp.full((bp1,), _INF, jnp.int32).at[owner].min(page_q), n
        )
        q_pg = q[owner][:, None]
        # the splice's roll-by-one, in page space: lane 0 takes the LAST
        # lane of the doc's previous page (first pages read the null page's
        # zero, which the select below never keeps: q >= 0 always)
        shifted_elem = jnp.concatenate(
            [elem[prev_page, p - 1][:, None], elem[:, :-1]], axis=1
        )
        shifted_char = jnp.concatenate(
            [chars[prev_page, p - 1][:, None], chars[:, :-1]], axis=1
        )
        new_elem = jnp.where(
            pos < q_pg, elem,
            jnp.where(pos == q_pg, op[owner][:, None], shifted_elem),
        )
        new_char = jnp.where(
            pos < q_pg, chars,
            jnp.where(pos == q_pg, ch[owner][:, None], shifted_char),
        )
        apply_pg = ok[owner][:, None]
        return (
            jnp.where(apply_pg, new_elem, elem),
            jnp.where(apply_pg, new_char, chars),
            jnp.where(ok, n + 1, n),
            ov | (live & ~found) | (live & (n >= cap)),
        )

    return lax.fori_loop(0, k_ins, body, (pool_elem, pool_char, n0, ov0))


def _ragged_exists_lax(pool_elem, owner, del_target, k_del):
    """(B+1, KD) bool: does each delete target exist among its doc's pool
    pages.  One traced-bound fori over the round's max true delete count;
    columns beyond a doc's own count carry target 0 (dead: the caller's
    ``live`` mask gates them) so skipping them preserves byte equality."""
    bp1, kd = del_target.shape

    def body(j, ex):
        tgt = lax.dynamic_index_in_dim(del_target, j, axis=1, keepdims=False)
        hit_pg = jnp.any(pool_elem == tgt[owner][:, None], axis=1)  # (N,)
        col = jnp.zeros((bp1,), bool).at[owner].max(hit_pg)
        return lax.dynamic_update_index_in_dim(ex, col, j, axis=1)

    return lax.fori_loop(0, k_del, body, jnp.zeros((bp1, kd), bool))


def apply_batch_ragged(
    pool_elem,
    pool_char,
    aux,  # tuple of dense (D, ...) arrays in PAGED_AUX_FIELDS order
    row_idx,  # (B,) batch doc rows (every row real — no padding axis)
    owner,  # (N,) batch-local owner per pool page (B = unowned)
    pos_base,  # (N,) first slot position of each page within its doc
    prev_page,  # (N,) preceding page of the same doc (0 = null page)
    page_count,  # (B,) TRUE allocated pages per row
    page_table,  # (B, max_doc_pages) pool page per doc-page (pallas plane)
    encoded_arrays,  # the apply_batch stream tuple with (B, ...) doc axes
    ins_counts,  # (B,) int32 TRUE per-doc insert counts (data, not shape)
    del_counts,  # (B,) int32 TRUE per-doc delete counts (data, not shape)
    *,
    ragged_impl: str = "auto",
):
    """The ragged twin of kernel.apply_batch_paged: apply one round's
    streams directly against pool pages, no gather/scatter, no buckets.
    Returns ``(pool_elem, pool_char, aux)`` updated.

    The compiled shape is (pool, streams, plan planes) only — per-doc op
    and page counts are data (the lax walk trips its fori loops on the
    batch maxima as TRACED bounds; the pallas grid cells trip on each
    doc's own count), so every round of a session (and every doc mix
    within a round) reuses ONE executable."""
    if len(encoded_arrays) == 6:
        ins_ref, ins_op, ins_char, del_target, marks, mark_count = encoded_arrays
        maps, map_count = None, None
    else:
        (ins_ref, ins_op, ins_char, del_target, marks, mark_count,
         maps, map_count) = encoded_arrays
    impl = ragged_impl
    if impl == "auto":
        # backend-default sniff only: under the jit wrappers "auto" was
        # already resolved against the REAL pool array at the boundary
        # (apply_batch_ragged_jit); in here the pool is a tracer whose
        # sharding is unobservable, so the array adds nothing
        impl = resolve_ragged_impl()

    p = pool_elem.shape[1]
    ins_counts = jnp.asarray(ins_counts, jnp.int32)
    del_counts = jnp.asarray(del_counts, jnp.int32)
    n0 = aux[_NUM_SLOTS][row_idx]
    ov0 = aux[_OVERFLOW][row_idx]
    cap = page_count.astype(jnp.int32) * jnp.int32(p)
    k_del = jnp.max(del_counts, initial=0)

    if impl in ("pallas", "pallas_interpret"):
        from .ragged_pallas import ragged_vmem_ok

        if not ragged_vmem_ok(page_table.shape[1], p, ins_op.shape[1]):
            impl = "lax"
    if impl in ("pallas", "pallas_interpret"):
        from .ragged_pallas import ragged_insert_pallas

        pool_elem, pool_char, n1, ov1 = ragged_insert_pallas(
            pool_elem, pool_char, page_table, page_count, ins_counts,
            n0, ov0, cap, ins_ref, ins_op, ins_char,
            interpret=(impl == "pallas_interpret"),
        )
    elif impl == "lax":
        k_ins = jnp.max(ins_counts, initial=0)
        pool_elem, pool_char, n_pad, ov_pad = _ragged_insert_lax(
            pool_elem, pool_char, owner, pos_base, prev_page,
            _pad_row(n0), _pad_row(ov0), _pad_row(cap),
            _pad_row(ins_ref), _pad_row(ins_op), _pad_row(ins_char), k_ins,
        )
        n1, ov1 = n_pad[:-1], ov_pad[:-1]
    else:
        raise ValueError(f"unknown ragged_impl: {ragged_impl!r}")

    exists = _ragged_exists_lax(pool_elem, owner, _pad_row(del_target), k_del)

    # phases 2-4 run on the dense aux rows exactly as the padded path does
    # (they never touch the element planes: the one elem read — the delete
    # target-exists scan — was precomputed against pool pages above)
    sub = {f: a[row_idx] for f, a in zip(PAGED_AUX_FIELDS, aux)}
    b = ins_ref.shape[0]
    dummy = jnp.zeros((b, 1), jnp.int32)
    state = PackedDocs(elem_id=dummy, char=dummy, **sub)
    state = state._replace(num_slots=n1, overflow=ov1)
    state = jax.vmap(
        lambda s, d, m, mc, ex: _post_insert_doc(s, d, m, mc, exists=ex)
    )(state, del_target, marks, mark_count, exists[:b])
    if maps is not None:
        state = jax.vmap(_apply_map_doc)(
            state, maps["p_obj"], maps["p_key"], maps["p_op"],
            maps["p_kind"], maps["p_val"], map_count,
        )
    aux = tuple(
        a.at[row_idx].set(getattr(state, f))
        for f, a in zip(PAGED_AUX_FIELDS, aux)
    )
    return pool_elem, pool_char, aux


_apply_batch_ragged_jit = jax.jit(
    apply_batch_ragged, static_argnames=("ragged_impl",),
    donate_argnums=(0, 1, 2),
)
_apply_batch_ragged_jit_nodonate = jax.jit(
    apply_batch_ragged, static_argnames=("ragged_impl",),
)


def apply_batch_ragged_jit(pool_elem, pool_char, aux, row_idx, owner,
                           pos_base, prev_page, page_count, page_table,
                           encoded_arrays, ins_counts, del_counts, *,
                           ragged_impl: str = "auto",
                           donate: bool | None = None):
    """jit-compiled :func:`apply_batch_ragged`; the pool operands are
    donated per kernel.resolve_state_donation (or the explicit ``donate``)
    — rebind to the returned triple either way.  ``"auto"`` resolves at
    the boundary from the pool arrays' placement."""
    if ragged_impl == "auto":
        ragged_impl = resolve_ragged_impl(pool_elem)
    if donate is None:
        donate = resolve_state_donation(pool_elem)
    fn = _apply_batch_ragged_jit if donate else _apply_batch_ragged_jit_nodonate
    args = (pool_elem, pool_char, aux, row_idx, owner, pos_base, prev_page,
            page_count, page_table, encoded_arrays, ins_counts, del_counts)
    if GLOBAL_DEVPROF.enabled:
        _note_dispatch("apply_batch_ragged", fn, args,
                       dict(ragged_impl=ragged_impl))
    return fn(*args, ragged_impl=ragged_impl)


def plan_arrays(plan):
    """Device operands of a store/ragged.RaggedPlan — the static-per-epoch
    plane set a session uploads once per allocation epoch, not per round."""
    return (
        jnp.asarray(plan.row_idx),
        jnp.asarray(plan.owner),
        jnp.asarray(plan.pos_base),
        jnp.asarray(plan.prev_page),
        jnp.asarray(plan.page_count),
        jnp.asarray(plan.page_table),
    )


def stream_counts(enc, rows=None):
    """Host-side ``(ins_counts, del_counts)`` int32 pair for one round's
    staging buffers: the TRUE per-doc insert / delete counts (restricted
    to ``rows`` when given).  These are the loop trip counts the ragged
    program runs — the quantity that makes padded stream slots free.

    Streaming round buffers carry the counts directly; EncodedBatch does
    not, so fall back to counting live stream entries (a live insert has a
    nonzero op id, a live delete a nonzero target)."""
    import numpy as np

    ins = getattr(enc, "ins_count", None)
    if ins is not None:
        ins = np.asarray(ins, np.int32)
        dels = np.asarray(enc.del_count, np.int32)
    else:
        ins = np.count_nonzero(np.asarray(enc.ins_op), axis=1).astype(np.int32)
        dels = np.count_nonzero(
            np.asarray(enc.del_target), axis=1
        ).astype(np.int32)
    if rows is not None:
        ins = ins[rows]
        dels = dels[rows]
    return ins, dels
