"""Patch emission for the batched path: host diff over resolved states.

The scalar oracle emits reference-shaped incremental patches from inside op
application (core/doc.py, mirroring src/micromerge.ts:1006-1138).  The device
path deliberately does not — per-op effects would serialize the kernel — so
patches are recovered here as a *host diff between two resolved states*
(SURVEY §7 L4: "patch emission: dense state + host diff").

The diff is exact, not heuristic: characters are keyed by their CRDT element
identity ``(ctr, actor)``, which is stable for a character's whole life, so
insert/delete placement never mis-aligns the way a text-only diff can.  Mark
changes on surviving characters become addMark/removeMark patches over
contiguous runs.  Patch semantics match the reference's (and
``testing/accumulate.py``'s) application model: text patches first, indices
against the evolving document; mark patches afterwards in final coordinates.
"""

from __future__ import annotations

from difflib import SequenceMatcher
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from ..core.types import Patch
from ..utils.interning import Interner, OrderedActorTable
from .packed import unpack_id
from .resolve import ResolvedDocs

#: one visible character: (element identity, character, flattened MarkMap)
CharState = Tuple[Any, str, Dict[str, Any]]


def doc_chars_device(
    resolved: ResolvedDocs,
    doc_index: int,
    attr_table: Interner,
    elem_ids: np.ndarray,
    actor_table: OrderedActorTable,
    comment_table: "Interner | None" = None,
) -> List[CharState]:
    """Per-character (identity, char, marks) for one device doc.  Identities
    are unpacked to ``(ctr, actor_string)`` so they are stable across the
    device and scalar paths (a doc that demotes mid-session keeps diffing
    cleanly).  Mark extraction is shared with the span read path
    (decode.DocMarkDecoder) so the two can never diverge."""
    from .decode import DocMarkDecoder

    dec = DocMarkDecoder(resolved, doc_index, attr_table, comment_table)
    out: List[CharState] = []
    for slot in np.nonzero(dec.visible)[0]:
        ctr, actor_idx = unpack_id(int(elem_ids[slot]))
        out.append(
            ((ctr, actor_table.lookup(actor_idx)), chr(int(dec.chars[slot])),
             dec.marks_at(slot))
        )
    return out


def doc_chars_scalar(doc, path=("text",)) -> List[CharState]:
    """Per-character (identity, char, marks) from a scalar oracle Doc."""
    spans = doc.get_text_with_formatting(list(path))
    meta = doc.list_metadata(tuple(path))
    ids = [el.elem_id for el in meta if not el.deleted]
    out: List[CharState] = []
    pos = 0
    for span in spans:
        for ch in span["text"]:
            out.append((ids[pos], ch, _copy_marks(span["marks"])))
            pos += 1
    return out


from ..core.spans import copy_marks as _copy_marks  # shared MarkMap copy


def diff_patches(
    before: Sequence[CharState],
    after: Sequence[CharState],
    path: Sequence[str] = ("text",),
) -> List[Patch]:
    """Reference-shaped patches transforming ``before`` into ``after``.

    Application model (testing/accumulate.py): replay in order; text patches
    use indices valid at their point in the stream, mark patches come last in
    final-document coordinates.  ``accumulate_patches(as_insert_patches(
    before) + diff_patches(before, after))`` equals the span form of
    ``after`` — asserted by the differential tests.
    """
    path = list(path)
    ids_before = [c[0] for c in before]
    ids_after = [c[0] for c in after]
    sm = SequenceMatcher(a=ids_before, b=ids_after, autojunk=False)

    patches: List[Patch] = []
    mark_patches: List[Patch] = []
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag in ("delete", "replace"):
            patches.append(
                {"action": "delete", "path": path, "index": j1, "count": i2 - i1}
            )
        if tag in ("insert", "replace"):
            # one insert patch per run of identically-marked characters (an
            # insert patch carries a single marks dict for all its values)
            run_start = j1
            while run_start < j2:
                run_end = run_start + 1
                while run_end < j2 and after[run_end][2] == after[run_start][2]:
                    run_end += 1
                patches.append(
                    {
                        "action": "insert",
                        "path": path,
                        "index": run_start,
                        "values": [after[j][1] for j in range(run_start, run_end)],
                        "marks": _copy_marks(after[run_start][2]),
                    }
                )
                run_start = run_end
        if tag == "equal":
            for offset in range(i2 - i1):
                deltas = _mark_deltas(before[i1 + offset][2], after[j1 + offset][2])
                for delta in deltas:
                    _extend_mark_run(mark_patches, delta, j1 + offset, path)

    return patches + mark_patches


def _mark_deltas(before: Dict[str, Any], after: Dict[str, Any]):
    """(action, markType, attrs) changes turning ``before`` marks into
    ``after`` marks for one character."""
    deltas: List[Tuple[str, str, Any]] = []
    types = set(before) | set(after)
    for mark_type in sorted(types):
        b, a = before.get(mark_type), after.get(mark_type)
        if b == a:
            continue
        if mark_type == "comment":
            b_ids = {c["id"] for c in (b or [])}
            a_ids = {c["id"] for c in (a or [])}
            for cid in sorted(a_ids - b_ids):
                deltas.append(("addMark", "comment", {"id": cid}))
            for cid in sorted(b_ids - a_ids):
                deltas.append(("removeMark", "comment", {"id": cid}))
        elif a is None:
            deltas.append(("removeMark", mark_type, None))
        else:
            attrs = {k: v for k, v in a.items() if k != "active"}
            deltas.append(("addMark", mark_type, attrs or None))
    return deltas


def _extend_mark_run(
    mark_patches: List[Patch], delta, position: int, path: List[str]
) -> None:
    """Merge a per-character mark delta into the trailing run patch when it
    is contiguous and identical; otherwise open a new patch."""
    action, mark_type, attrs = delta
    for patch in reversed(mark_patches):
        if (
            patch["action"] == action
            and patch["markType"] == mark_type
            and patch.get("attrs") == attrs
        ):
            if patch["endIndex"] == position:
                patch["endIndex"] = position + 1
                return
            break  # same delta but non-contiguous: new run
    patch: Patch = {
        "action": action,
        "path": path,
        "startIndex": position,
        "endIndex": position + 1,
        "markType": mark_type,
    }
    if attrs is not None:
        patch["attrs"] = attrs
    mark_patches.append(patch)


def as_insert_patches(chars: Sequence[CharState], path=("text",)) -> List[Patch]:
    """A state expressed as the insert-patch stream that builds it from
    empty (the before-stream for differential tests)."""
    return diff_patches([], chars, path)
