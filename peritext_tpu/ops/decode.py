"""Host-side decoding: resolved device state -> format spans / plain text.

The inverse boundary of ops/encode.py: un-interns attrs, converts codepoints
back to characters, and flattens per-character mark state into the same
merged span lists the scalar oracle's ``get_text_with_formatting`` returns,
so the two paths are directly comparable (byte-equality oracle).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.spans import add_characters_to_spans
from ..core.types import FormatSpan
from ..schema import MARK_INDEX
from ..utils.interning import Interner
from .resolve import ResolvedDocs

_STRONG = MARK_INDEX["strong"]
_EM = MARK_INDEX["em"]
_LINK = MARK_INDEX["link"]


class DocMarkDecoder:
    """Per-slot MarkMap extraction for ONE doc of a (numpy-converted)
    ResolvedDocs batch — the single source of truth for turning resolved
    device arrays into mark dicts, shared by the span read path and the
    patch diff path (ops/patches.py).  Per-doc rows are sliced once at
    construction; ``marks_at`` is then cheap per visible slot."""

    def __init__(self, resolved: ResolvedDocs, doc_index: int, attr_table: Interner,
                 comment_table: Interner | None = None):
        d = doc_index
        self._attrs = attr_table
        # comment-plane ids may live in a separate (per-doc dense) table:
        # they index capacity-C planes, unlike link attrs which are opaque
        self._comment_ids = comment_table if comment_table is not None else attr_table
        self.visible = np.asarray(resolved.visible[d])
        self.chars = np.asarray(resolved.char[d])
        self._lww = np.asarray(resolved.lww_active[d])
        self._link_attr = np.asarray(resolved.link_attr[d])
        # unpack the (W, S) uint32 comment bitmask to a (W*32, S) bool plane
        bits = np.asarray(resolved.comment_bits[d])
        shifts = np.arange(32, dtype=np.uint32)
        self._comments = (
            (bits[:, None, :] >> shifts[None, :, None]) & 1
        ).astype(bool).reshape(-1, bits.shape[-1])

    def marks_at(self, slot: int) -> dict:
        marks: dict = {}
        if self._lww[_STRONG, slot]:
            marks["strong"] = {"active": True}
        if self._lww[_EM, slot]:
            marks["em"] = {"active": True}
        if self._lww[_LINK, slot]:
            url = self._attrs.lookup(int(self._link_attr[slot]))
            marks["link"] = {"active": True, "url": url}
        active_ids = sorted(
            self._comment_ids.lookup(int(c))
            for c in np.nonzero(self._comments[:, slot])[0]
        )
        if active_ids:
            marks["comment"] = [{"id": cid} for cid in active_ids]
        return marks


def decode_doc_spans(
    resolved: ResolvedDocs, doc_index: int, attr_table: Interner,
    comment_table: Interner | None = None,
) -> List[FormatSpan]:
    """Decode one document of a (numpy-converted) ResolvedDocs batch."""
    dec = DocMarkDecoder(resolved, doc_index, attr_table, comment_table)
    spans: List[FormatSpan] = []
    for slot in np.nonzero(dec.visible)[0]:
        add_characters_to_spans(
            [chr(int(dec.chars[slot]))], dec.marks_at(slot), spans
        )
    return spans


def decode_doc_text(resolved: ResolvedDocs, doc_index: int) -> str:
    visible = np.asarray(resolved.visible[doc_index])
    chars = np.asarray(resolved.char[doc_index])
    return "".join(chr(int(c)) for c in chars[visible])


def decode_doc_root(state, resolved: ResolvedDocs, doc_index: int, keys: Interner):
    """Materialize one doc's root map from its device LWW registers — the
    device twin of the scalar oracle's ``Doc.root`` (object graph walk from
    the reference's nested object store, src/micromerge.ts:520-539).

    ``state`` is a (numpy-converted) PackedDocs; VK_TEXT registers expand to
    the visible character list so ``root == oracle.root`` exactly."""
    from .packed import (
        OBJ_ROOT,
        VK_DELETED,
        VK_FALSE,
        VK_INT,
        VK_NULL,
        VK_OBJ,
        VK_STR,
        VK_TEXT,
        VK_TRUE,
    )

    d = doc_index
    n = int(np.asarray(state.num_regs[d]))
    r_obj = np.asarray(state.r_obj[d])[:n]
    r_key = np.asarray(state.r_key[d])[:n]
    r_op = np.asarray(state.r_op[d])[:n]
    r_kind = np.asarray(state.r_kind[d])[:n]
    r_val = np.asarray(state.r_val[d])[:n]
    visible = np.asarray(resolved.visible[d])
    chars = np.asarray(resolved.char[d])

    by_container: dict = {}
    for i in range(n):
        if r_op[i] == 0:
            continue
        by_container.setdefault(int(r_obj[i]), []).append(i)

    def build(obj_id: int, path: frozenset = frozenset()) -> dict:
        if obj_id in path:  # malformed peer: self/ancestor reference
            return {}
        path = path | {obj_id}
        out: dict = {}
        for i in by_container.get(obj_id, ()):
            kind = int(r_kind[i])
            if kind == VK_DELETED:
                continue
            key = keys.lookup(int(r_key[i]))
            if kind == VK_STR:
                out[key] = keys.lookup(int(r_val[i]))
            elif kind == VK_INT:
                out[key] = int(r_val[i])
            elif kind == VK_TRUE:
                out[key] = True
            elif kind == VK_FALSE:
                out[key] = False
            elif kind == VK_NULL:
                out[key] = None
            elif kind == VK_OBJ:
                out[key] = build(int(r_val[i]), path)
            elif kind == VK_TEXT:
                out[key] = [chr(int(c)) for c in chars[visible]]
        return out

    return build(OBJ_ROOT)
