"""Host-side decoding: resolved device state -> format spans / plain text.

The inverse boundary of ops/encode.py: un-interns attrs, converts codepoints
back to characters, and flattens per-character mark state into the same
merged span lists the scalar oracle's ``get_text_with_formatting`` returns,
so the two paths are directly comparable (byte-equality oracle).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.spans import add_characters_to_spans
from ..core.types import FormatSpan
from ..schema import MARK_INDEX
from ..utils.interning import Interner
from .resolve import ResolvedDocs

_STRONG = MARK_INDEX["strong"]
_EM = MARK_INDEX["em"]
_LINK = MARK_INDEX["link"]


def decode_slot_marks(
    resolved: ResolvedDocs, doc_index: int, slot: int, attr_table: Interner
) -> dict:
    """Flattened MarkMap for one visible slot of a (numpy-converted)
    ResolvedDocs batch — the single source of truth for turning resolved
    device arrays into mark dicts (shared by the span read path and the
    patch diff path, ops/patches.py)."""
    d = doc_index
    lww = np.asarray(resolved.lww_active[d])
    marks: dict = {}
    if lww[_STRONG, slot]:
        marks["strong"] = {"active": True}
    if lww[_EM, slot]:
        marks["em"] = {"active": True}
    if lww[_LINK, slot]:
        url = attr_table.lookup(int(np.asarray(resolved.link_attr[d])[slot]))
        marks["link"] = {"active": True, "url": url}
    comments = np.asarray(resolved.comment_active[d])
    active_ids = sorted(
        attr_table.lookup(int(c)) for c in np.nonzero(comments[:, slot])[0]
    )
    if active_ids:
        marks["comment"] = [{"id": cid} for cid in active_ids]
    return marks


def decode_doc_spans(
    resolved: ResolvedDocs, doc_index: int, attr_table: Interner
) -> List[FormatSpan]:
    """Decode one document of a (numpy-converted) ResolvedDocs batch."""
    d = doc_index
    visible = np.asarray(resolved.visible[d])
    chars = np.asarray(resolved.char[d])

    spans: List[FormatSpan] = []
    for slot in np.nonzero(visible)[0]:
        marks = decode_slot_marks(resolved, d, slot, attr_table)
        add_characters_to_spans([chr(int(chars[slot]))], marks, spans)
    return spans


def decode_doc_text(resolved: ResolvedDocs, doc_index: int) -> str:
    visible = np.asarray(resolved.visible[doc_index])
    chars = np.asarray(resolved.char[doc_index])
    return "".join(chr(int(c)) for c in chars[visible])
