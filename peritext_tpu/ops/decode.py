"""Host-side decoding: resolved device state -> format spans / plain text.

The inverse boundary of ops/encode.py: un-interns attrs, converts codepoints
back to characters, and flattens per-character mark state into the same
merged span lists the scalar oracle's ``get_text_with_formatting`` returns,
so the two paths are directly comparable (byte-equality oracle).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.spans import add_characters_to_spans
from ..core.types import FormatSpan
from ..schema import MARK_INDEX
from ..utils.interning import Interner
from .resolve import ResolvedDocs

_STRONG = MARK_INDEX["strong"]
_EM = MARK_INDEX["em"]
_LINK = MARK_INDEX["link"]


class DocMarkDecoder:
    """Per-slot MarkMap extraction for ONE doc of a (numpy-converted)
    ResolvedDocs batch — the single source of truth for turning resolved
    device arrays into mark dicts, shared by the span read path and the
    patch diff path (ops/patches.py).  Per-doc rows are sliced once at
    construction; ``marks_at`` is then cheap per visible slot."""

    def __init__(self, resolved: ResolvedDocs, doc_index: int, attr_table: Interner,
                 comment_table: Interner | None = None):
        d = doc_index
        self._attrs = attr_table
        # comment-plane ids may live in a separate (per-doc dense) table:
        # they index capacity-C planes, unlike link attrs which are opaque
        self._comment_ids = comment_table if comment_table is not None else attr_table
        self.visible = np.asarray(resolved.visible[d])
        self.chars = np.asarray(resolved.char[d])
        self._lww = np.asarray(resolved.lww_active[d])
        self._link_attr = np.asarray(resolved.link_attr[d])
        # unpack the (W, S) uint32 comment bitmask to a (W*32, S) bool plane
        bits = np.asarray(resolved.comment_bits[d])
        shifts = np.arange(32, dtype=np.uint32)
        self._comments = (
            (bits[:, None, :] >> shifts[None, :, None]) & 1
        ).astype(bool).reshape(-1, bits.shape[-1])

    def marks_at(self, slot: int) -> dict:
        marks: dict = {}
        if self._lww[_STRONG, slot]:
            marks["strong"] = {"active": True}
        if self._lww[_EM, slot]:
            marks["em"] = {"active": True}
        if self._lww[_LINK, slot]:
            url = self._attrs.lookup(int(self._link_attr[slot]))
            marks["link"] = {"active": True, "url": url}
        active_ids = sorted(
            self._comment_ids.lookup(int(c))
            for c in np.nonzero(self._comments[:, slot])[0]
        )
        if active_ids:
            marks["comment"] = [{"id": cid} for cid in active_ids]
        return marks


def decode_doc_spans(
    resolved: ResolvedDocs, doc_index: int, attr_table: Interner,
    comment_table: Interner | None = None,
) -> List[FormatSpan]:
    """Decode one document of a (numpy-converted) ResolvedDocs batch."""
    dec = DocMarkDecoder(resolved, doc_index, attr_table, comment_table)
    spans: List[FormatSpan] = []
    for slot in np.nonzero(dec.visible)[0]:
        add_characters_to_spans(
            [chr(int(dec.chars[slot]))], dec.marks_at(slot), spans
        )
    return spans


def _comment_ids_from_bits(row_bits, comment_table: Interner):
    """Sorted comment-id strings from one slot's packed uint32 words."""
    ids = []
    for w in range(row_bits.shape[0]):
        v = int(row_bits[w])
        while v:
            b = (v & -v).bit_length() - 1
            ids.append(comment_table.lookup(w * 32 + b))
            v &= v - 1
    return sorted(ids)


def _block_flat(resolved: ResolvedDocs, doc_mask=None):
    """Flatten a (numpy-converted) resolved block to its visible characters
    in doc-major order plus per-char mark features and run boundaries.

    Returns ``(rows, cols, seg_starts, seg_ends, text, lww, link, bits)``
    where a segment is a maximal run of same-doc, identically-marked
    characters — the unit all read paths decode at (marks are built once per
    segment, not per character).  ``doc_mask`` (bool (B,)) excludes docs
    (fallback/overflow rows may hold residue with out-of-table ids)."""
    vis = np.asarray(resolved.visible)
    if doc_mask is not None:
        vis = vis & np.asarray(doc_mask)[:, None]
    rows, cols = np.nonzero(vis)
    if len(rows) == 0:
        return rows, cols, rows, rows, "", None, None, None, None
    chars = np.asarray(resolved.char)[rows, cols]
    lww = np.asarray(resolved.lww_active)[rows, :, cols]  # (N, T)
    link = np.asarray(resolved.link_attr)[rows, cols]
    bits = np.asarray(resolved.comment_bits)[rows, :, cols]  # (N, W) uint32
    feat = np.concatenate(
        [lww.astype(np.int64), link[:, None].astype(np.int64),
         bits.astype(np.int64)],
        axis=1,
    )
    boundary = np.ones(len(rows), bool)
    boundary[1:] = (rows[1:] != rows[:-1]) | np.any(feat[1:] != feat[:-1], axis=1)
    seg_starts = np.nonzero(boundary)[0]
    seg_ends = np.append(seg_starts[1:], len(rows))
    text = "".join(map(chr, chars.tolist()))
    return rows, cols, seg_starts, seg_ends, text, lww, link, bits, feat


def _segment_marks(s: int, lww, link, bits, attrs: Interner,
                   comments: Interner) -> dict:
    marks: dict = {}
    if lww[s, _STRONG]:
        marks["strong"] = {"active": True}
    if lww[s, _EM]:
        marks["em"] = {"active": True}
    if lww[s, _LINK]:
        marks["link"] = {"active": True, "url": attrs.lookup(int(link[s]))}
    if bits[s].any():
        active = _comment_ids_from_bits(bits[s], comments)
        if active:
            marks["comment"] = [{"id": cid} for cid in active]
    return marks


def _copy_marks(marks: dict) -> dict:
    """Copy a memoized marks dict ALL the way down (values are tiny:
    ``{"active": True}``, a link dict, a comment-id list) so a caller
    mutating one span's marks — including nested values — cannot reformat
    unrelated spans sharing the memo entry (ADVICE r3)."""
    return {
        k: [dict(e) for e in v] if isinstance(v, list) else dict(v)
        for k, v in marks.items()
    }


def decode_block_spans(resolved: ResolvedDocs, attr_of, comment_of, doc_mask=None):
    """Vectorized span decode of a WHOLE resolved block in one pass.

    The per-doc reader (:func:`decode_doc_spans`) walks slots in Python —
    fine for one doc, quadratic pain for a 100K-doc sweep.  Here the visible
    characters of every doc are extracted with numpy, mark-run boundaries
    are computed vectorized, and Python touches only SEGMENTS (runs of
    identically-marked text), which the differential tests assert produces
    exactly the per-doc reader's spans.

    ``attr_of(d)`` / ``comment_of(d)`` return the attr / comment-id interner
    for block-local doc d; ``doc_mask`` excludes (fallback/overflow) docs.
    Returns a span list per doc (empty for docs with no visible text).

    Marks dicts are MEMOIZED by (interner identities, feature bytes) — a
    100K-doc sweep has millions of segments but only dozens of distinct mark
    combinations, so the per-segment ``_segment_marks`` work collapses to a
    dict hit.  Each span still gets its OWN tiny copy: a caller mutating one
    span's marks must not silently reformat unrelated spans (ADVICE r3)."""
    n_docs = np.asarray(resolved.visible).shape[0]
    return _spans_from_flat(_block_flat(resolved, doc_mask), attr_of,
                            comment_of, n_docs)


def _spans_from_flat(flat, attr_of, comment_of, n_docs: int):
    """Shared segment loop of the span decoders (full and compact flatten
    feed the same tuple).  One memoized ``_segment_marks`` per distinct
    (interners, features) key; one defensive copy per span (ADVICE r3)."""
    out = [[] for _ in range(n_docs)]
    rows, _, seg_starts, seg_ends, text, lww, link, bits, feat = flat
    cache: dict = {}
    for s, e in zip(seg_starts.tolist(), seg_ends.tolist()):
        d = int(rows[s])
        attrs, comments = attr_of(d), comment_of(d)
        # the per-doc comment table only shapes marks when the segment has
        # comment bits — keying on its identity otherwise would give every
        # doc its own memo row and defeat the cross-doc dedup entirely
        has_c = bool(bits[s].any())
        key = (id(attrs), id(comments) if has_c else 0, feat[s].tobytes())
        marks = cache.get(key)
        if marks is None:
            marks = cache[key] = _segment_marks(s, lww, link, bits, attrs, comments)
        out[d].append({"marks": _copy_marks(marks), "text": text[s:e]})
    return out


def _char_states_from_flat(flat, packed_elems, actor_table, attr_of,
                           comment_of, n_docs: int):
    """Shared segment loop of the char-state decoders: per-doc
    ``(identity, char, marks)`` lists.  Characters in a segment share ONE
    per-segment marks copy (diff consumers compare marks by equality, and
    the shared reference makes adjacent-equality checks O(1)); the memoized
    master never escapes, so mutating one segment's marks can't reformat
    another (ADVICE r3).  ``packed_elems`` are the (N,) packed elem ids
    aligned with the flat character order."""
    from .packed import ACTOR_BITS, MAX_ACTORS

    out = [[] for _ in range(n_docs)]
    rows, _, seg_starts, seg_ends, text, lww, link, bits, feat = flat
    if len(rows) == 0:
        return out
    ctrs = (packed_elems >> ACTOR_BITS).tolist()
    actor_idx = (packed_elems & MAX_ACTORS).tolist()
    actor_names = [actor_table.lookup(i) for i in range(len(actor_table))]
    cache: dict = {}
    for s, e in zip(seg_starts.tolist(), seg_ends.tolist()):
        d = int(rows[s])
        attrs, comments = attr_of(d), comment_of(d)
        has_c = bool(bits[s].any())  # see _spans_from_flat on the memo key
        key = (id(attrs), id(comments) if has_c else 0, feat[s].tobytes())
        marks = cache.get(key)
        if marks is None:
            marks = cache[key] = _segment_marks(s, lww, link, bits, attrs, comments)
        seg_marks = _copy_marks(marks)
        bucket = out[d]
        for j in range(s, e):
            bucket.append(((ctrs[j], actor_names[actor_idx[j]]), text[j], seg_marks))
    return out


def block_char_states(resolved: ResolvedDocs, elem_id_block, actor_table,
                      attr_of, comment_of, doc_mask=None):
    """Per-doc ``(identity, char, marks)`` lists for a whole block — the
    batched twin of ops/patches.doc_chars_device, and the full-plane oracle
    the compact variant is differentially tested against."""
    vis = np.asarray(resolved.visible)
    flat = _block_flat(resolved, doc_mask)
    rows, cols = flat[0], flat[1]
    if len(rows) == 0:
        return [[] for _ in range(vis.shape[0])]
    packed = np.asarray(elem_id_block)[rows, cols]
    return _char_states_from_flat(flat, packed, actor_table, attr_of,
                                  comment_of, vis.shape[0])


def decode_doc_text(resolved: ResolvedDocs, doc_index: int) -> str:
    visible = np.asarray(resolved.visible[doc_index])
    chars = np.asarray(resolved.char[doc_index])
    return "".join(chr(int(c)) for c in chars[visible])


def decode_doc_root(state, resolved: ResolvedDocs, doc_index: int, keys: Interner):
    """Materialize one doc's root map from its device LWW registers — the
    device twin of the scalar oracle's ``Doc.root`` (object graph walk from
    the reference's nested object store, src/micromerge.ts:520-539).

    ``state`` is a (numpy-converted) PackedDocs; VK_TEXT registers expand to
    the visible character list so ``root == oracle.root`` exactly."""
    from .packed import (
        OBJ_ROOT,
        VK_DELETED,
        VK_FALSE,
        VK_INT,
        VK_NULL,
        VK_OBJ,
        VK_STR,
        VK_TEXT,
        VK_TRUE,
    )

    d = doc_index
    n = int(np.asarray(state.num_regs[d]))
    r_obj = np.asarray(state.r_obj[d])[:n]
    r_key = np.asarray(state.r_key[d])[:n]
    r_op = np.asarray(state.r_op[d])[:n]
    r_kind = np.asarray(state.r_kind[d])[:n]
    r_val = np.asarray(state.r_val[d])[:n]
    visible = np.asarray(resolved.visible[d])
    chars = np.asarray(resolved.char[d])

    by_container: dict = {}
    for i in range(n):
        if r_op[i] == 0:
            continue
        by_container.setdefault(int(r_obj[i]), []).append(i)

    def build(obj_id: int, path: frozenset = frozenset()) -> dict:
        if obj_id in path:  # malformed peer: self/ancestor reference
            return {}
        path = path | {obj_id}
        out: dict = {}
        for i in by_container.get(obj_id, ()):
            kind = int(r_kind[i])
            if kind == VK_DELETED:
                continue
            key = keys.lookup(int(r_key[i]))
            if kind == VK_STR:
                out[key] = keys.lookup(int(r_val[i]))
            elif kind == VK_INT:
                out[key] = int(r_val[i])
            elif kind == VK_TRUE:
                out[key] = True
            elif kind == VK_FALSE:
                out[key] = False
            elif kind == VK_NULL:
                out[key] = None
            elif kind == VK_OBJ:
                out[key] = build(int(r_val[i]), path)
            elif kind == VK_TEXT:
                out[key] = [chr(int(c)) for c in chars[visible]]
        return out

    return build(OBJ_ROOT)


# -- compact (visible-prefix) block decode ----------------------------------
#
# The full resolved planes are (D, S)-shaped over SLOT capacity; a sweep
# transfers them host-side even though only the visible characters matter
# (a typical streamed doc holds ~100 visible chars in 512 slots, and the
# tunneled d2h link is the sweep's wall clock).  CompactBlock is the same
# information gathered device-side to a visible-prefix layout of bucketed
# width W << S, with the LWW planes bit-packed: ~5x less transfer per doc.


class CompactBlock:
    """Numpy visible-prefix planes of one resolved block.

    ``n_vis[d]`` chars of doc ``d`` live at columns ``0..n_vis[d]`` of:
    ``char``(int32), ``elem``(packed int32 elem ids), ``link``(int32 attr
    ids), ``lww``(uint8 bitmask over LWW mark types), ``comment_bits``
    ((D, Wd, W) uint32).  ``overflow`` is carried for the device/replay
    routing mask."""

    __slots__ = ("n_vis", "char", "elem", "link", "lww", "comment_bits",
                 "overflow")

    def __init__(self, n_vis, char, elem, link, lww, comment_bits, overflow):
        self.n_vis = np.asarray(n_vis)
        self.char = np.asarray(char)
        self.elem = np.asarray(elem)
        self.link = np.asarray(link)
        self.lww = np.asarray(lww)
        self.comment_bits = np.asarray(comment_bits)
        self.overflow = np.asarray(overflow)

    @property
    def nbytes(self) -> int:
        return sum(getattr(self, f).nbytes for f in self.__slots__)


def _block_flat_compact(c: CompactBlock, doc_mask=None):
    """Compact-layout twin of :func:`_block_flat`: flatten to doc-major
    visible characters + per-char features + same-mark run boundaries.
    Columns are CHARACTER indices (the compact layout's native order), and
    the per-type LWW bools are unpacked from the bitmask plane."""
    d, w = c.char.shape
    vis = np.arange(w)[None, :] < c.n_vis[:, None]
    if doc_mask is not None:
        vis &= np.asarray(doc_mask)[:, None]
    rows, cols = np.nonzero(vis)
    if len(rows) == 0:
        return rows, cols, rows, rows, "", None, None, None, None
    chars = c.char[rows, cols]
    lww_bits = c.lww[rows, cols].astype(np.int64)  # (N,) packed
    shifts = np.arange(8, dtype=np.int64)  # uint8 plane: up to 8 LWW types
    lww = ((lww_bits[:, None] >> shifts[None, :]) & 1).astype(bool)  # (N, 8)
    link = c.link[rows, cols]
    bits = (
        c.comment_bits[rows, :, cols]
        if c.comment_bits.shape[1]
        else np.zeros((len(rows), 0), np.uint32)
    )  # (N, Wd) uint32
    feat = np.concatenate(
        [lww_bits[:, None], link[:, None].astype(np.int64), bits.astype(np.int64)],
        axis=1,
    )
    boundary = np.ones(len(rows), bool)
    boundary[1:] = (rows[1:] != rows[:-1]) | np.any(feat[1:] != feat[:-1], axis=1)
    seg_starts = np.nonzero(boundary)[0]
    seg_ends = np.append(seg_starts[1:], len(rows))
    text = "".join(map(chr, chars.tolist()))
    return rows, cols, seg_starts, seg_ends, text, lww, link, bits, feat


def decode_block_spans_compact(c: CompactBlock, attr_of, comment_of,
                               doc_mask=None):
    """:func:`decode_block_spans` over a :class:`CompactBlock` — identical
    output (tests/test_device_path.py pins compact == full on the same
    block), one visible-prefix transfer instead of full (D, S) planes."""
    return _spans_from_flat(_block_flat_compact(c, doc_mask), attr_of,
                            comment_of, c.char.shape[0])


def block_char_states_compact(c: CompactBlock, actor_table, attr_of,
                              comment_of, doc_mask=None):
    """:func:`block_char_states` over a :class:`CompactBlock`: per-doc
    ``(identity, char, marks)`` lists, identities unpacked from the
    compacted elem-id plane."""
    flat = _block_flat_compact(c, doc_mask)
    rows, cols = flat[0], flat[1]
    if len(rows) == 0:
        return [[] for _ in range(c.char.shape[0])]
    packed = c.elem[rows, cols]
    return _char_states_from_flat(flat, packed, actor_table, attr_of,
                                  comment_of, c.char.shape[0])
