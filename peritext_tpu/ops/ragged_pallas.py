"""Ragged Pallas insert kernel: grid over docs, page table scalar-prefetched.

The padded kernel (ops/pallas_insert.py) blocks a dense ``(D, S)`` state
onto the grid — every doc pays the widest doc's slot axis.  This kernel is
its ragged twin over the page pool: the grid is one cell per BATCH DOC, the
doc's page table rides in scalar-prefetch memory (so the gather targets are
known before the cell body runs), and each cell

1. DMA-gathers the doc's TRUE pages from the pool (ANY/HBM refs) into a
   ``(max_doc_pages, P)`` VMEM scratch window,
2. runs the doc's TRUE insert count through the RGA insert loop on that
   window — the same masked-reduction formulation as pallas_insert
   (argmax is unsupported by Mosaic; min/max over ``where`` masks), with
   the padded path's roll-by-one spelled as a lane shift whose lane-0
   values come from the previous page row,
3. DMA-scatters the pages back.

``input_output_aliases`` pins the pool in place (indices count flattened
leaves INCLUDING the scalar-prefetch operands — the megablox convention).
Unowned pool pages are untouched by construction: no page table points at
them.  The per-doc ``(max_doc_pages, P)`` window is deliberately the unit
the v5e-8 mesh roadmap item shards.

Loop bounds (pages gathered, inserts applied) come from the prefetched
scalar planes, so one compiled program serves every doc mix — the whole
point of the ragged layout (see ops/ragged.py; the recompile sentinel
pins it).

CPU runs this kernel under ``interpret=True`` only (differential tests);
the production CPU path is the lax pool walk.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either so the
# module imports across the versions the container may carry.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

#: reuse the padded kernel's "no position" sentinel discipline; far above
#: any slot position, far below int32 max so +1 arithmetic stays safe
#: (a plain int: Pallas kernels may not close over device constants)
_INF = 2**30

#: VMEM ceiling / working budget, matching ops/pallas_insert.py
_VMEM_LIMIT = 100 * 1024 * 1024
_VMEM_BUDGET = 72 * 1024 * 1024


def ragged_vmem_ok(gmax: int, page_size: int, k_ins: int) -> bool:
    """Whether one grid cell's residents (two (gmax, P) scratch windows +
    the doc's stream block) fit the VMEM working budget."""
    scratch = 2 * gmax * page_size * 4
    stream = 3 * k_ins * 4
    return scratch + stream <= _VMEM_BUDGET


def _ragged_insert_kernel(
    # scalar prefetch
    page_table_ref,  # (B, Gmax) pool page per (doc, doc-page) — 0 = null
    page_count_ref,  # (B,) true page count per doc
    ins_count_ref,   # (B,) true insert count per doc
    # inputs
    pool_elem_hbm,   # (N, P) ANY — aliased with out
    pool_char_hbm,   # (N, P) ANY — aliased with out
    n_ref,           # (1, 1) block of (B, 1)
    ov_ref,          # (1, 1) block of (B, 1) int32
    cap_ref,         # (1, 1) block of (B, 1)
    ins_ref_ref,     # (1, KI) block
    ins_op_ref,      # (1, KI) block
    ins_char_ref,    # (1, KI) block
    # outputs
    out_elem_hbm,    # (N, P) ANY — IS pool_elem_hbm (aliased)
    out_char_hbm,    # (N, P) ANY — IS pool_char_hbm (aliased)
    n_out_ref,       # (1, 1)
    ov_out_ref,      # (1, 1)
    # scratch
    elem_scr,        # VMEM (Gmax, P)
    char_scr,        # VMEM (Gmax, P)
    dma_sem,
):
    i = pl.program_id(0)
    g = page_count_ref[i]
    gmax, p = elem_scr.shape

    # beyond-allocation window rows must read as zero (they carry stale
    # VMEM between grid cells otherwise; the insert math relies on unused
    # slots being zero only up to the doc's own cap, but the exists-free
    # design below never writes them back, so zeroing is purely defensive)
    elem_scr[...] = jnp.zeros((gmax, p), jnp.int32)
    char_scr[...] = jnp.zeros((gmax, p), jnp.int32)

    def _gather(j, _):
        pg = page_table_ref[i, j]
        cp = pltpu.make_async_copy(
            pool_elem_hbm.at[pl.ds(pg, 1), :], elem_scr.at[pl.ds(j, 1), :],
            dma_sem,
        )
        cp.start()
        cp.wait()
        cp = pltpu.make_async_copy(
            pool_char_hbm.at[pl.ds(pg, 1), :], char_scr.at[pl.ds(j, 1), :],
            dma_sem,
        )
        cp.start()
        cp.wait()
        return 0

    lax.fori_loop(0, g, _gather, 0)

    n0 = n_ref[0, 0]
    ov0 = ov_ref[0, 0]
    cap = cap_ref[0, 0]
    lane = lax.broadcasted_iota(jnp.int32, (gmax, p), 1)
    grow = lax.broadcasted_iota(jnp.int32, (gmax, p), 0)
    pos = grow * jnp.int32(p) + lane

    def _body(k, carry):
        n, ov = carry
        ref = ins_ref_ref[0, k]
        op = ins_op_ref[0, k]
        ch = ins_char_ref[0, k]
        live = op != 0
        is_head = ref == 0
        elem = elem_scr[...]
        chars = char_scr[...]
        # first matching position via masked min (ids unique, so the min
        # of matches IS the padded argmax); Mosaic has no argmax
        match = (elem == ref) & (pos < n)
        pmin = jnp.min(jnp.where(match, pos, _INF))
        found = is_head | (pmin < _INF)
        pref = jnp.where(is_head, jnp.int32(-1), pmin)
        ok = live & found & (n < cap)
        candidate = (pos > pref) & (pos < n) & (elem < op)
        q = jnp.minimum(jnp.min(jnp.where(candidate, pos, _INF)), n)
        # fold rejected steps into a no-op: q beyond every window position
        q = jnp.where(ok, q, jnp.int32(gmax * p))
        # the splice's roll-by-one across the 2D window: lane 0 of each
        # page row takes the LAST lane of the previous page row
        rolled_e = jnp.roll(elem, 1, axis=1)
        rolled_c = jnp.roll(chars, 1, axis=1)
        prev_last_e = jnp.roll(elem[:, p - 1 : p], 1, axis=0)
        prev_last_c = jnp.roll(chars[:, p - 1 : p], 1, axis=0)
        shifted_e = jnp.where(lane == 0, prev_last_e, rolled_e)
        shifted_c = jnp.where(lane == 0, prev_last_c, rolled_c)
        elem_scr[...] = jnp.where(
            pos < q, elem, jnp.where(pos == q, op, shifted_e)
        )
        char_scr[...] = jnp.where(
            pos < q, chars, jnp.where(pos == q, ch, shifted_c)
        )
        return (
            jnp.where(ok, n + 1, n),
            ov | ((live & ~found) | (live & (n >= cap))).astype(jnp.int32),
        )

    n1, ov1 = lax.fori_loop(0, ins_count_ref[i], _body, (n0, ov0))
    n_out_ref[0, 0] = n1
    ov_out_ref[0, 0] = ov1

    def _scatter(j, _):
        pg = page_table_ref[i, j]
        cp = pltpu.make_async_copy(
            elem_scr.at[pl.ds(j, 1), :], out_elem_hbm.at[pl.ds(pg, 1), :],
            dma_sem,
        )
        cp.start()
        cp.wait()
        cp = pltpu.make_async_copy(
            char_scr.at[pl.ds(j, 1), :], out_char_hbm.at[pl.ds(pg, 1), :],
            dma_sem,
        )
        cp.start()
        cp.wait()
        return 0

    lax.fori_loop(0, g, _scatter, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ragged_insert_pallas(
    pool_elem, pool_char, page_table, page_count, ins_counts,
    n0, ov0, cap, ins_ref, ins_op, ins_char, *, interpret: bool = False,
):
    """Ragged insert phase over the pool (module doc).  ``n0``/``ov0``/
    ``cap``/streams carry plain (B,)/(B, KI) batch axes — no inert row; the
    kernel never reduces across docs.  Returns ``(pool_elem, pool_char,
    n, ov)`` with ``ov`` as bool."""
    b, ki = ins_op.shape
    n, p = pool_elem.shape
    gmax = page_table.shape[1]
    col = lambda w: pl.BlockSpec(  # noqa: E731
        (1, w), lambda i, *_: (i, 0), memory_space=pltpu.VMEM
    )
    pool = pl.BlockSpec(memory_space=pltpu.ANY)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[pool, pool, col(1), col(1), col(1), col(ki), col(ki), col(ki)],
        out_specs=[pool, pool, col(1), col(1)],
        scratch_shapes=[
            pltpu.VMEM((gmax, p), jnp.int32),
            pltpu.VMEM((gmax, p), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out_elem, out_char, n1, ov1 = pl.pallas_call(
        _ragged_insert_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n, p), pool_elem.dtype),
            jax.ShapeDtypeStruct((n, p), pool_char.dtype),
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
        ],
        # flattened-leaf indices, scalar-prefetch operands included
        # (page_table=0, page_count=1, ins_counts=2, pool_elem=3, pool_char=4)
        input_output_aliases={3: 0, 4: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(
        page_table, page_count, ins_counts,
        pool_elem, pool_char,
        n0[:, None].astype(jnp.int32),
        ov0[:, None].astype(jnp.int32),
        cap[:, None].astype(jnp.int32),
        ins_ref, ins_op, ins_char,
    )
    return out_elem, out_char, n1[:, 0], ov1[:, 0] != 0
