"""Host-side encoding: change logs -> padded split-stream device tensors.

The irregular, string-y work that is wrong for the TPU happens here: causal
sorting (parallel/causal.py), actor/attr interning (utils/interning.py),
boundary-anchor flattening, and padding/bucketing.

Ops are split into three streams per document, exploiting the commutation
structure of the packed representation (ops/packed.py):

* **inserts** — the only truly sequential stream (each insert's position
  depends on prior inserts); consumed by the per-doc fori_loop.
* **deletes** — idempotent tombstone sets; they commute with each other and
  with inserts' *placement* (the RGA skip compares only element ids,
  reference src/micromerge.ts:1201-1208), so they apply as one vectorized
  pass after all inserts.
* **marks** — grow-only table rows; they are encoded host-side directly in
  mark-table layout and appended with one vectorized scatter.

All identifiers are packed int32s (packed.pack_id).  Documents whose logs the
device path cannot express (non-text objects, too many actors/ops) are routed
to the scalar-oracle fallback (``EncodedBatch.fallback_docs``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.opids import HEAD
from ..core.types import AFTER, BEFORE, END_OF_TEXT, START_OF_TEXT, Boundary, Change
from ..parallel.causal import causal_sort
from ..schema import MARK_INDEX
from ..utils.interning import Interner, OrderedActorTable
from .packed import (
    BK_AFTER,
    BK_BEFORE,
    BK_END_OF_TEXT,
    BK_START_OF_TEXT,
    MA_ADD,
    MA_REMOVE,
    MAX_ACTORS,
    MAX_CTR,
    pack_id,
)

_BK = {
    BEFORE: BK_BEFORE,
    AFTER: BK_AFTER,
    START_OF_TEXT: BK_START_OF_TEXT,
    END_OF_TEXT: BK_END_OF_TEXT,
}

#: Columns of a host-side mark row, in PackedDocs mark-table order.
MARK_COLS = (
    "m_action",
    "m_type",
    "m_start_kind",
    "m_start_elem",
    "m_end_kind",
    "m_end_elem",
    "m_op",
    "m_attr",
)


@dataclass
class EncodedBatch:
    """Padded split-stream batch plus intern tables for decoding outputs."""

    # insert stream (D, KI)
    ins_ref: np.ndarray  # packed predecessor elem (0 = HEAD)
    ins_op: np.ndarray  # packed op id (0 = pad)
    ins_char: np.ndarray  # int32 codepoint
    # delete stream (D, KD); packed target elem (0 = pad)
    del_target: np.ndarray
    # mark stream (D, KM) per MARK_COLS
    marks: Dict[str, np.ndarray]
    mark_count: np.ndarray  # int32 (D,)
    num_ops: np.ndarray  # int32 (D,) total encoded ops (stats)
    actor_tables: List[OrderedActorTable]
    attr_tables: List[Interner]
    #: doc indices the device path cannot express; resolved by the oracle
    fallback_docs: List[int] = field(default_factory=list)

    @property
    def num_docs(self) -> int:
        return self.ins_op.shape[0]


class _DocStreams:
    def __init__(self) -> None:
        self.ins: List[Tuple[int, int, int]] = []  # (ref, op, char)
        self.dels: List[int] = []
        self.marks: List[Tuple[int, ...]] = []  # MARK_COLS order


def _pack_opid(opid, actors: OrderedActorTable) -> int:
    ctr, actor = opid
    if ctr > MAX_CTR:
        raise OverflowError(f"op counter {ctr} exceeds packed capacity")
    return pack_id(ctr, actors.intern(actor))


def _pack_boundary(b: Boundary, actors: OrderedActorTable) -> Tuple[int, int]:
    if b.elem is not None:
        return _BK[b.kind], _pack_opid(b.elem, actors)
    return _BK[b.kind], 0


def encode_doc(
    changes: Sequence[Change],
    actors: OrderedActorTable,
    attrs: Interner,
    text_obj=None,
):
    """Split one document's causally-sorted changes into three streams.
    Returns (_DocStreams, ok, text_obj); ok=False -> host fallback.
    ``text_obj`` (the op id of the document's text list) carries across
    incremental rounds for streaming sessions."""
    streams = _DocStreams()

    for change in changes:
        for op in change.ops:
            if op.action == "makeList" and text_obj is None:
                text_obj = op.opid
                continue
            if op.obj != text_obj:
                return streams, False, text_obj
            if op.action == "set" and op.insert:
                ref = 0 if op.elem_id is HEAD else _pack_opid(op.elem_id, actors)
                streams.ins.append((ref, _pack_opid(op.opid, actors), ord(op.value)))
            elif op.action == "del":
                streams.dels.append(_pack_opid(op.elem_id, actors))
            elif op.action in ("addMark", "removeMark"):
                sk, se = _pack_boundary(op.start, actors)
                ek, ee = _pack_boundary(op.end, actors)
                attr = 0
                if op.attrs:
                    # key-presence, not truthiness: an empty url/id is a value
                    if "url" in op.attrs:
                        attr = attrs.intern(op.attrs["url"])
                    elif "id" in op.attrs:
                        attr = attrs.intern(op.attrs["id"])
                streams.marks.append(
                    (
                        MA_ADD if op.action == "addMark" else MA_REMOVE,
                        MARK_INDEX[op.mark_type],
                        sk,
                        se,
                        ek,
                        ee,
                        _pack_opid(op.opid, actors),
                        attr,
                    )
                )
            else:
                return streams, False, text_obj  # makeMap / map ops: host fallback
    return streams, True, text_obj


class DocEncoder:
    """Persistent per-document encoder for incremental (streaming) rounds.

    The actor table must be declared up front: packed int32 op-ID comparison
    equals (counter, actor-string) order only when actor indices follow string
    order, and a table that grows mid-session could violate that
    (utils/interning.OrderedActorTable).  A change from an undeclared actor
    marks the encoder failed; the streaming layer then falls back to scalar
    replay for that document.
    """

    def __init__(self, actor_names) -> None:
        self.actors = OrderedActorTable(actor_names)
        self.attrs = Interner()
        self.text_obj = None
        self.ok = len(self.actors) - 1 <= MAX_ACTORS

    def encode_increment(self, ordered_changes: Sequence[Change]):
        """Encode one round's causally-ordered new changes.  Returns
        (_DocStreams, ok); once not ok, the encoder stays failed."""
        if not self.ok:
            return _DocStreams(), False
        try:
            streams, ok, self.text_obj = encode_doc(
                ordered_changes, self.actors, self.attrs, self.text_obj
            )
        except (OverflowError, KeyError):  # ctr overflow / undeclared actor
            ok = False
            streams = _DocStreams()
        self.ok = ok
        return streams, ok


def _round8(n: int) -> int:
    return max(8, -(-n // 8) * 8)


def encode_workloads(
    workloads: Sequence[Dict[str, List[Change]]],
    insert_capacity: Optional[int] = None,
    delete_capacity: Optional[int] = None,
    mark_capacity: Optional[int] = None,
) -> EncodedBatch:
    """Encode a batch of per-doc change-log sets (dict actor -> [Change])."""
    per_doc: List[Optional[_DocStreams]] = []
    actor_tables: List[OrderedActorTable] = []
    attr_tables: List[Interner] = []
    fallback: List[int] = []

    for doc_index, queues in enumerate(workloads):
        all_changes = [ch for log in queues.values() for ch in log]
        ordered = causal_sort(all_changes)
        actor_set = {ch.actor for ch in all_changes} | {
            op.opid[1] for ch in all_changes for op in ch.ops
        }
        actors = OrderedActorTable(actor_set)
        attrs = Interner()
        # len(actors) includes the reserved index-0 None slot, so the largest
        # assigned actor index is len(actors) - 1, which must fit ACTOR_BITS.
        ok = len(actors) - 1 <= MAX_ACTORS
        streams = _DocStreams()
        if ok:
            try:
                streams, ok, _ = encode_doc(ordered, actors, attrs)
            except OverflowError:
                ok = False
        if not ok:
            fallback.append(doc_index)
            streams = _DocStreams()
        per_doc.append(streams)
        actor_tables.append(actors)
        attr_tables.append(attrs)

    return pad_doc_streams(
        per_doc,
        fallback,
        actor_tables,
        attr_tables,
        insert_capacity=insert_capacity,
        delete_capacity=delete_capacity,
        mark_capacity=mark_capacity,
    )


def pad_doc_streams(
    per_doc: Sequence[_DocStreams],
    fallback: List[int],
    actor_tables: List[OrderedActorTable],
    attr_tables: List[Interner],
    insert_capacity: Optional[int] = None,
    delete_capacity: Optional[int] = None,
    mark_capacity: Optional[int] = None,
) -> EncodedBatch:
    """Pad per-doc split streams into dense (D, K) arrays.  Docs exceeding a
    fixed capacity are appended to ``fallback`` (shape buckets are static so
    XLA compiles once per bucket)."""
    d = len(per_doc)
    ki = insert_capacity or _round8(max((len(s.ins) for s in per_doc), default=0))
    kd = delete_capacity or _round8(max((len(s.dels) for s in per_doc), default=0))
    km = mark_capacity or _round8(max((len(s.marks) for s in per_doc), default=0))

    ins_ref = np.zeros((d, ki), np.int32)
    ins_op = np.zeros((d, ki), np.int32)
    ins_char = np.zeros((d, ki), np.int32)
    del_target = np.zeros((d, kd), np.int32)
    marks = {col: np.zeros((d, km), np.int32) for col in MARK_COLS}
    mark_count = np.zeros(d, np.int32)
    num_ops = np.zeros(d, np.int32)

    for i, streams in enumerate(per_doc):
        if i in fallback:
            continue
        if len(streams.ins) > ki or len(streams.dels) > kd or len(streams.marks) > km:
            fallback.append(i)  # over this shape bucket: oracle fallback
            continue
        if streams.ins:
            arr = np.asarray(streams.ins, np.int32)
            ins_ref[i, : len(arr)] = arr[:, 0]
            ins_op[i, : len(arr)] = arr[:, 1]
            ins_char[i, : len(arr)] = arr[:, 2]
        if streams.dels:
            del_target[i, : len(streams.dels)] = streams.dels
        if streams.marks:
            arr = np.asarray(streams.marks, np.int32)
            for c, col in enumerate(MARK_COLS):
                marks[col][i, : len(arr)] = arr[:, c]
            mark_count[i] = len(arr)
        num_ops[i] = len(streams.ins) + len(streams.dels) + len(streams.marks)

    return EncodedBatch(
        ins_ref=ins_ref,
        ins_op=ins_op,
        ins_char=ins_char,
        del_target=del_target,
        marks=marks,
        mark_count=mark_count,
        num_ops=num_ops,
        actor_tables=actor_tables,
        attr_tables=attr_tables,
        fallback_docs=sorted(fallback),
    )
