"""Host-side encoding: change logs -> padded split-stream device tensors.

The irregular, string-y work that is wrong for the TPU happens here: causal
sorting (parallel/causal.py), actor/attr interning (utils/interning.py),
boundary-anchor flattening, and padding/bucketing.

Ops are split into three streams per document, exploiting the commutation
structure of the packed representation (ops/packed.py):

* **inserts** — the only truly sequential stream (each insert's position
  depends on prior inserts); consumed by the per-doc fori_loop.
* **deletes** — idempotent tombstone sets; they commute with each other and
  with inserts' *placement* (the RGA skip compares only element ids,
  reference src/micromerge.ts:1201-1208), so they apply as one vectorized
  pass after all inserts.
* **marks** — grow-only table rows; they are encoded host-side directly in
  mark-table layout and appended with one vectorized scatter.

All identifiers are packed int32s (packed.pack_id).  Documents whose logs the
device path cannot express (non-text objects, too many actors/ops) are routed
to the scalar-oracle fallback (``EncodedBatch.fallback_docs``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.opids import HEAD, ROOT
from ..core.types import AFTER, BEFORE, END_OF_TEXT, START_OF_TEXT, Boundary, Change
from ..parallel.causal import causal_sort
from ..schema import MARK_INDEX
from ..utils.interning import Interner, OrderedActorTable
from .packed import (
    BK_AFTER,
    BK_BEFORE,
    BK_END_OF_TEXT,
    BK_START_OF_TEXT,
    MA_ADD,
    MA_REMOVE,
    MAX_ACTORS,
    MAX_CTR,
    OBJ_ROOT,
    VK_DELETED,
    VK_FALSE,
    VK_INT,
    VK_NULL,
    VK_OBJ,
    VK_STR,
    VK_TEXT,
    VK_TRUE,
    pack_id,
)

_BK = {
    BEFORE: BK_BEFORE,
    AFTER: BK_AFTER,
    START_OF_TEXT: BK_START_OF_TEXT,
    END_OF_TEXT: BK_END_OF_TEXT,
}

#: Columns of a host-side mark row, in PackedDocs mark-table order.
MARK_COLS = (
    "m_action",
    "m_type",
    "m_start_kind",
    "m_start_elem",
    "m_end_kind",
    "m_end_elem",
    "m_op",
    "m_attr",
)

# canonical map-register column order lives in packed.py (device & host
# share one definition); re-exported here for stream-filling callers
from .packed import MAP_STREAM_COLS  # noqa: E402  (grouped with MARK_COLS)


@dataclass
class EncodedBatch:
    """Padded split-stream batch plus intern tables for decoding outputs."""

    # insert stream (D, KI)
    ins_ref: np.ndarray  # packed predecessor elem (0 = HEAD)
    ins_op: np.ndarray  # packed op id (0 = pad)
    ins_char: np.ndarray  # int32 codepoint
    # delete stream (D, KD); packed target elem (0 = pad)
    del_target: np.ndarray
    # mark stream (D, KM) per MARK_COLS
    marks: Dict[str, np.ndarray]
    mark_count: np.ndarray  # int32 (D,)
    # map-register stream (D, KP) per MAP_STREAM_COLS
    map_ops: Dict[str, np.ndarray]
    map_count: np.ndarray  # int32 (D,)
    num_ops: np.ndarray  # int32 (D,) total encoded ops (stats)
    actor_tables: List[OrderedActorTable]
    attr_tables: List[Interner]
    #: per-doc interner for map keys and string values
    map_tables: List[Interner]
    #: doc indices the device path cannot express; resolved by the oracle
    fallback_docs: List[int] = field(default_factory=list)

    @property
    def num_docs(self) -> int:
        return self.ins_op.shape[0]


class _DocStreams:
    def __init__(self) -> None:
        self.ins: List[Tuple[int, int, int]] = []  # (ref, op, char)
        self.dels: List[int] = []
        self.marks: List[Tuple[int, ...]] = []  # MARK_COLS order
        self.maps: List[Tuple[int, int, int, int, int]] = []  # MAP_STREAM_COLS


def _pack_opid(opid, actors: OrderedActorTable) -> int:
    ctr, actor = opid
    if ctr > MAX_CTR:
        raise OverflowError(f"op counter {ctr} exceeds packed capacity")
    return pack_id(ctr, actors.intern(actor))


def _pack_boundary(b: Boundary, actors: OrderedActorTable) -> Tuple[int, int]:
    if b.elem is not None:
        return _BK[b.kind], _pack_opid(b.elem, actors)
    return _BK[b.kind], 0


def _encode_value(value, keys: Interner):
    """Map-set value -> (VK_*, payload), or None when inexpressible on
    device (nested containers, floats, out-of-range ints -> oracle)."""
    if isinstance(value, bool):
        return (VK_TRUE if value else VK_FALSE), 0
    if value is None:
        return VK_NULL, 0
    if isinstance(value, str):
        return VK_STR, keys.intern(value)
    if isinstance(value, int) and -(2**31) <= value < 2**31:
        return VK_INT, value
    return None


def encode_doc(
    changes: Sequence[Change],
    actors: OrderedActorTable,
    attrs: Interner,
    keys: Interner,
    text_obj=None,
    map_objs: Optional[set] = None,
    text_key: Optional[str] = None,
):
    """Split one document's causally-sorted changes into four streams
    (text inserts / deletes / marks, plus map-register writes).
    Returns (_DocStreams, ok, text_obj, text_key); ok=False -> host fallback.
    ``text_obj`` (the op id of the document's text list), ``map_objs`` (the
    packed ids of known map objects, mutated in place) and ``text_key`` carry
    across incremental rounds for streaming sessions."""
    streams = _DocStreams()
    if map_objs is None:
        map_objs = set()

    for change in changes:
        for op in change.ops:
            if text_obj is not None and op.obj == text_obj:
                if op.action == "set" and op.insert:
                    ref = 0 if op.elem_id is HEAD else _pack_opid(op.elem_id, actors)
                    streams.ins.append((ref, _pack_opid(op.opid, actors), ord(op.value)))
                elif op.action == "del":
                    streams.dels.append(_pack_opid(op.elem_id, actors))
                elif op.action in ("addMark", "removeMark"):
                    sk, se = _pack_boundary(op.start, actors)
                    ek, ee = _pack_boundary(op.end, actors)
                    attr = 0
                    if op.attrs:
                        # key-presence, not truthiness: empty url/id is a value
                        if "url" in op.attrs:
                            attr = attrs.intern(op.attrs["url"])
                        elif "id" in op.attrs:
                            attr = attrs.intern(op.attrs["id"])
                    streams.marks.append(
                        (
                            MA_ADD if op.action == "addMark" else MA_REMOVE,
                            MARK_INDEX[op.mark_type],
                            sk,
                            se,
                            ek,
                            ee,
                            _pack_opid(op.opid, actors),
                            attr,
                        )
                    )
                else:
                    return streams, False, text_obj, text_key
                continue

            # Map-object ops (reference src/micromerge.ts:1151-1175): the
            # containing object must be the root or a known child map.
            if op.obj is ROOT:
                pobj = OBJ_ROOT
            else:
                pobj = _pack_opid(op.obj, actors)
                if pobj not in map_objs:
                    return streams, False, text_obj, text_key
            if op.key is None:
                return streams, False, text_obj, text_key
            popid = _pack_opid(op.opid, actors)
            pkey = keys.intern(op.key)
            if op.action == "makeList":
                # exactly one list (the text sequence) is device-expressible
                if text_obj is not None:
                    return streams, False, text_obj, text_key
                text_obj = op.opid
                text_key = op.key
                streams.maps.append((pobj, pkey, popid, VK_TEXT, popid))
            elif op.action == "makeMap":
                map_objs.add(popid)
                streams.maps.append((pobj, pkey, popid, VK_OBJ, popid))
            elif op.action == "set" and not op.insert:
                encoded = _encode_value(op.value, keys)
                if encoded is None:
                    return streams, False, text_obj, text_key
                streams.maps.append((pobj, pkey, popid, *encoded))
            elif op.action == "del":
                streams.maps.append((pobj, pkey, popid, VK_DELETED, 0))
            else:
                return streams, False, text_obj, text_key
    return streams, True, text_obj, text_key


class DocEncoder:
    """Persistent per-document encoder for incremental (streaming) rounds.

    The actor table must be declared up front: packed int32 op-ID comparison
    equals (counter, actor-string) order only when actor indices follow string
    order, and a table that grows mid-session could violate that
    (utils/interning.OrderedActorTable).  A change from an undeclared actor
    marks the encoder failed; the streaming layer then falls back to scalar
    replay for that document.
    """

    def __init__(self, actor_names) -> None:
        self.actors = OrderedActorTable(actor_names)
        self.attrs = Interner()
        self.keys = Interner()
        self.text_obj = None
        self.text_key: Optional[str] = None
        self.map_objs: set = set()
        self.ok = len(self.actors) - 1 <= MAX_ACTORS

    def encode_increment(self, ordered_changes: Sequence[Change]):
        """Encode one round's causally-ordered new changes.  Returns
        (_DocStreams, ok); once not ok, the encoder stays failed."""
        if not self.ok:
            return _DocStreams(), False
        try:
            streams, ok, self.text_obj, self.text_key = encode_doc(
                ordered_changes, self.actors, self.attrs, self.keys,
                self.text_obj, self.map_objs, self.text_key,
            )
        except (OverflowError, KeyError):  # ctr overflow / undeclared actor
            ok = False
            streams = _DocStreams()
        self.ok = ok
        return streams, ok


def _round8(n: int) -> int:
    return max(8, -(-n // 8) * 8)


#: shared all-empty stream set: the stand-in for capacity-fallback docs in
#: grouped (paged) encoding — their real streams must not inflate a group's
#: widths, and their rows stay all-zero no-ops
_EMPTY_STREAMS = _DocStreams()


def encode_doc_streams(
    workloads: Sequence[Dict[str, List[Change]]],
):
    """The per-doc half of :func:`encode_workloads`: causal sort + intern +
    stream split for every doc, WITHOUT padding into a shared (D, K) shape.
    Returns ``(per_doc, fallback, actor_tables, attr_tables, map_tables)``.

    Exposed separately so the paged layout (api/batch.py ``layout="paged"``)
    can group docs by size BEFORE padding — each size bucket pads to its own
    widths via :func:`pad_doc_streams` instead of every doc paying the
    widest doc's stream width."""
    per_doc: List[Optional[_DocStreams]] = []
    actor_tables: List[OrderedActorTable] = []
    attr_tables: List[Interner] = []
    map_tables: List[Interner] = []
    fallback: List[int] = []

    for doc_index, queues in enumerate(workloads):
        all_changes = [ch for log in queues.values() for ch in log]
        ordered = causal_sort(all_changes)
        actor_set = {ch.actor for ch in all_changes} | {
            op.opid[1] for ch in all_changes for op in ch.ops
        }
        actors = OrderedActorTable(actor_set)
        attrs = Interner()
        keys = Interner()
        # len(actors) includes the reserved index-0 None slot, so the largest
        # assigned actor index is len(actors) - 1, which must fit ACTOR_BITS.
        ok = len(actors) - 1 <= MAX_ACTORS
        streams = _DocStreams()
        if ok:
            try:
                streams, ok, _, _ = encode_doc(ordered, actors, attrs, keys)
            except OverflowError:
                ok = False
        if not ok:
            fallback.append(doc_index)
            streams = _DocStreams()
        per_doc.append(streams)
        actor_tables.append(actors)
        attr_tables.append(attrs)
        map_tables.append(keys)

    return per_doc, fallback, actor_tables, attr_tables, map_tables


def encode_workloads(
    workloads: Sequence[Dict[str, List[Change]]],
    insert_capacity: Optional[int] = None,
    delete_capacity: Optional[int] = None,
    mark_capacity: Optional[int] = None,
    map_capacity: Optional[int] = None,
) -> EncodedBatch:
    """Encode a batch of per-doc change-log sets (dict actor -> [Change])."""
    per_doc, fallback, actor_tables, attr_tables, map_tables = (
        encode_doc_streams(workloads)
    )
    return pad_doc_streams(
        per_doc,
        fallback,
        actor_tables,
        attr_tables,
        map_tables=map_tables,
        insert_capacity=insert_capacity,
        delete_capacity=delete_capacity,
        mark_capacity=mark_capacity,
        map_capacity=map_capacity,
    )


def pad_doc_streams(
    per_doc: Sequence[_DocStreams],
    fallback: List[int],
    actor_tables: List[OrderedActorTable],
    attr_tables: List[Interner],
    map_tables: Optional[List[Interner]] = None,
    insert_capacity: Optional[int] = None,
    delete_capacity: Optional[int] = None,
    mark_capacity: Optional[int] = None,
    map_capacity: Optional[int] = None,
) -> EncodedBatch:
    """Pad per-doc split streams into dense (D, K) arrays.  Docs exceeding a
    fixed capacity are appended to ``fallback`` (shape buckets are static so
    XLA compiles once per bucket)."""
    d = len(per_doc)
    ki = insert_capacity or _round8(max((len(s.ins) for s in per_doc), default=0))
    kd = delete_capacity or _round8(max((len(s.dels) for s in per_doc), default=0))
    km = mark_capacity or _round8(max((len(s.marks) for s in per_doc), default=0))
    kp = map_capacity or _round8(max((len(s.maps) for s in per_doc), default=0))

    ins_ref = np.zeros((d, ki), np.int32)
    ins_op = np.zeros((d, ki), np.int32)
    ins_char = np.zeros((d, ki), np.int32)
    del_target = np.zeros((d, kd), np.int32)
    marks = {col: np.zeros((d, km), np.int32) for col in MARK_COLS}
    mark_count = np.zeros(d, np.int32)
    map_ops = {col: np.zeros((d, kp), np.int32) for col in MAP_STREAM_COLS}
    map_count = np.zeros(d, np.int32)
    num_ops = np.zeros(d, np.int32)

    for i, streams in enumerate(per_doc):
        if i in fallback:
            continue
        if (
            len(streams.ins) > ki or len(streams.dels) > kd
            or len(streams.marks) > km or len(streams.maps) > kp
        ):
            fallback.append(i)  # over this shape bucket: oracle fallback
            continue
        if streams.ins:
            arr = np.asarray(streams.ins, np.int32)
            ins_ref[i, : len(arr)] = arr[:, 0]
            ins_op[i, : len(arr)] = arr[:, 1]
            ins_char[i, : len(arr)] = arr[:, 2]
        if streams.dels:
            del_target[i, : len(streams.dels)] = streams.dels
        if streams.marks:
            arr = np.asarray(streams.marks, np.int32)
            for c, col in enumerate(MARK_COLS):
                marks[col][i, : len(arr)] = arr[:, c]
            mark_count[i] = len(arr)
        if streams.maps:
            arr = np.asarray(streams.maps, np.int32)
            for c, col in enumerate(MAP_STREAM_COLS):
                map_ops[col][i, : len(arr)] = arr[:, c]
            map_count[i] = len(arr)
        num_ops[i] = (
            len(streams.ins) + len(streams.dels)
            + len(streams.marks) + len(streams.maps)
        )

    return EncodedBatch(
        ins_ref=ins_ref,
        ins_op=ins_op,
        ins_char=ins_char,
        del_target=del_target,
        marks=marks,
        mark_count=mark_count,
        map_ops=map_ops,
        map_count=map_count,
        num_ops=num_ops,
        actor_tables=actor_tables,
        attr_tables=attr_tables,
        map_tables=map_tables if map_tables is not None else [Interner() for _ in range(d)],
        fallback_docs=sorted(fallback),
    )
