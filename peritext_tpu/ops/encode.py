"""Host-side encoding: change logs -> padded (doc x op) int32 tensors.

The hot device kernel (ops/kernel.py) consumes a causally pre-ordered, padded
op stream per document.  This module owns the irregular, string-y work that is
wrong for the TPU: causal sorting (parallel/causal.py), actor/attr interning
(utils/interning.py), boundary-anchor flattening, and padding/bucketing.

Encoded op record layout (one int32 row per internal op; F_* field indices):
every op kind uses a subset of the fields, zeros elsewhere.  Ops address the
document's single text list; workloads that touch other objects (nested maps)
are routed to the scalar oracle instead (``EncodeResult.fallback_docs``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.opids import HEAD
from ..core.types import BEFORE, AFTER, END_OF_TEXT, START_OF_TEXT, Boundary, Change
from ..parallel.causal import causal_sort
from ..schema import MARK_INDEX
from ..utils.interning import Interner, OrderedActorTable
from .packed import BK_AFTER, BK_BEFORE, BK_END_OF_TEXT, BK_START_OF_TEXT

# Field indices of an encoded op row.
F_KIND = 0
F_OP_CTR = 1
F_OP_ACTOR = 2
F_REF_CTR = 3  # insert: predecessor elem (0,0 = HEAD); delete: target elem
F_REF_ACTOR = 4
F_START_KIND = 5
F_START_CTR = 6
F_START_ACTOR = 7
F_END_KIND = 8
F_END_CTR = 9
F_END_ACTOR = 10
F_MARK_TYPE = 11
F_ATTR = 12
F_CHAR = 13
NUM_FIELDS = 14

# Op kinds.
K_PAD = 0
K_INSERT = 1
K_DELETE = 2
K_ADD_MARK = 3
K_REMOVE_MARK = 4

_BK = {BEFORE: BK_BEFORE, AFTER: BK_AFTER, START_OF_TEXT: BK_START_OF_TEXT, END_OF_TEXT: BK_END_OF_TEXT}


@dataclass
class EncodeResult:
    """Padded batch of op streams plus the intern tables to decode outputs."""

    ops: np.ndarray  # int32 (D, K, NUM_FIELDS)
    num_ops: np.ndarray  # int32 (D,)
    actor_tables: List[OrderedActorTable]
    attr_tables: List[Interner]
    #: doc indices whose logs the device path cannot express (non-text objects)
    fallback_docs: List[int] = field(default_factory=list)


def _boundary(b: Boundary, actors: OrderedActorTable) -> Tuple[int, int, int]:
    kind = _BK[b.kind]
    if b.elem is not None:
        return kind, b.elem[0], actors.intern(b.elem[1])
    return kind, 0, 0


def encode_doc_ops(
    changes: Sequence[Change],
    actors: OrderedActorTable,
    attrs: Interner,
) -> Tuple[Optional[np.ndarray], bool]:
    """Encode one document's causally-sorted changes into an (n, F) array.
    Returns (rows, ok); ok=False means this log needs the host fallback."""
    rows: List[List[int]] = []
    text_obj = None  # op ID of the makeList that created the text list

    for change in changes:
        for op in change.ops:
            if op.action == "makeList" and text_obj is None:
                text_obj = op.opid
                continue
            if op.obj != text_obj:
                return None, False  # non-text object: host fallback
            row = [0] * NUM_FIELDS
            row[F_OP_CTR] = op.opid[0]
            row[F_OP_ACTOR] = actors.intern(op.opid[1])
            if op.action == "set" and op.insert:
                row[F_KIND] = K_INSERT
                if op.elem_id is not HEAD:
                    row[F_REF_CTR] = op.elem_id[0]
                    row[F_REF_ACTOR] = actors.intern(op.elem_id[1])
                row[F_CHAR] = ord(op.value)
            elif op.action == "del":
                row[F_KIND] = K_DELETE
                row[F_REF_CTR] = op.elem_id[0]
                row[F_REF_ACTOR] = actors.intern(op.elem_id[1])
            elif op.action in ("addMark", "removeMark"):
                row[F_KIND] = K_ADD_MARK if op.action == "addMark" else K_REMOVE_MARK
                row[F_START_KIND], row[F_START_CTR], row[F_START_ACTOR] = _boundary(
                    op.start, actors
                )
                row[F_END_KIND], row[F_END_CTR], row[F_END_ACTOR] = _boundary(
                    op.end, actors
                )
                row[F_MARK_TYPE] = MARK_INDEX[op.mark_type]
                if op.attrs:
                    attr_value = op.attrs.get("url") or op.attrs.get("id")
                    if attr_value is not None:
                        row[F_ATTR] = attrs.intern(attr_value)
            else:
                return None, False  # makeMap / map set / del: host fallback
            rows.append(row)

    return np.asarray(rows, np.int32).reshape(-1, NUM_FIELDS), True


def encode_workloads(
    workloads: Sequence[Dict[str, List[Change]]],
    op_capacity: Optional[int] = None,
    overflow_to_fallback: bool = False,
) -> EncodeResult:
    """Encode a batch of per-doc change-log sets into padded device tensors.

    Each workload is a dict actor -> [Change] (one collaborative document).
    Logs are causally linearized per doc; the resulting op streams are padded
    to a common K (``op_capacity`` or the max stream length, rounded up to a
    multiple of 8 for layout friendliness).
    """
    per_doc_rows: List[Optional[np.ndarray]] = []
    actor_tables: List[OrderedActorTable] = []
    attr_tables: List[Interner] = []
    fallback: List[int] = []

    for doc_index, queues in enumerate(workloads):
        all_changes = [ch for log in queues.values() for ch in log]
        ordered = causal_sort(all_changes)
        actors = OrderedActorTable(
            {ch.actor for ch in all_changes}
            | {op.opid[1] for ch in all_changes for op in ch.ops}
        )
        attrs = Interner()
        rows, ok = encode_doc_ops(ordered, actors, attrs)
        if not ok:
            fallback.append(doc_index)
            rows = np.zeros((0, NUM_FIELDS), np.int32)
        per_doc_rows.append(rows)
        actor_tables.append(actors)
        attr_tables.append(attrs)

    max_ops = max((r.shape[0] for r in per_doc_rows), default=0)
    if op_capacity is None:
        op_capacity = max(8, -(-max_ops // 8) * 8)
    if max_ops > op_capacity and not overflow_to_fallback:
        raise ValueError(f"op stream length {max_ops} exceeds capacity {op_capacity}")

    batch = np.zeros((len(per_doc_rows), op_capacity, NUM_FIELDS), np.int32)
    num_ops = np.zeros(len(per_doc_rows), np.int32)
    for i, rows in enumerate(per_doc_rows):
        if rows.shape[0] > op_capacity:
            # too many ops for this shape bucket: route to the scalar oracle
            fallback.append(i)
            continue
        batch[i, : rows.shape[0]] = rows
        num_ops[i] = rows.shape[0]

    return EncodeResult(
        ops=batch,
        num_ops=num_ops,
        actor_tables=actor_tables,
        attr_tables=attr_tables,
        fallback_docs=fallback,
    )
