"""Frame-native ingest: wire bytes -> device streams without Python objects.

The object ingest path (parallel/streaming.py) walks Python ``Change``/
``Operation`` objects per op — fine for editors, but the bottleneck when a
host streams 100K docs of changes per round (SURVEY §5.8, BASELINE config 5).
This module is the native data-loader: a binary change frame (the DCN wire
format, parallel/codec.py) is parsed by the C++ core straight into flat int32
arrays (native.parse_changes), and everything after that — causal admission,
round budgeting, stream splitting, padding — is vectorized numpy over those
arrays.  Python-level objects appear only on slow paths (JSON-spillover ops,
undeclared actors), which demote a doc to the object/oracle path.

Uniform op-matrix column layout (kind in col 0): see pt_parse_changes in
native/src/native.cpp.  Identifiers are device-packed
(``ctr << ACTOR_BITS | actor``) from the moment of parsing.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from .. import native
from ..core.types import Operation
from ..schema import ALL_MARKS
from ..utils.interning import Interner, OrderedActorTable
from .packed import ACTOR_BITS, MAX_ACTORS, MAX_CTR, pack_id

KIND_INS = 0
KIND_DEL = 1
KIND_MARK = 2
KIND_JSON = 3
KIND_BAD = 4
KIND_SKIP = 5  # resolved makeList: consumed at parse time, no device op
KIND_MAP = 6  # map-register op (makeMap / map set / map del)
KIND_MAKELIST = 7  # wire-v2 native makeList row: adopted like the JSON form

#: op-matrix columns (see native.cpp): the mark row in device MARK_COLS order
#: is cols [3, 4, 5, 6, 7, 8, 2, 9].
_MARK_COL_ORDER = (3, 4, 5, 6, 7, 8, 2, 9)


@dataclass
class ParsedChanges:
    """Flat-array form of a set of changes (concatenable, sliceable)."""

    ch_actor: np.ndarray  # (N,) declared actor index
    ch_seq: np.ndarray  # (N,)
    dep_off: np.ndarray  # (N+1,)
    dep_actor: np.ndarray  # (ND,)
    dep_seq: np.ndarray  # (ND,)
    ops_off: np.ndarray  # (N+1,)
    ops: np.ndarray  # (NO, 10)
    cnt_ins: np.ndarray  # (N,)
    cnt_del: np.ndarray  # (N,)
    cnt_mark: np.ndarray  # (N,)
    cnt_map: np.ndarray  # (N,)

    @property
    def num_changes(self) -> int:
        return int(self.ch_actor.shape[0])

    @staticmethod
    def empty() -> "ParsedChanges":
        z = lambda *s: np.zeros(s, np.int32)  # noqa: E731
        return ParsedChanges(
            z(0), z(0), z(1), z(0), z(0), z(1), z(0, 10), z(0), z(0), z(0), z(0)
        )

    def concat(self, other: "ParsedChanges") -> "ParsedChanges":
        return ParsedChanges.concat_many([self, other])

    @staticmethod
    def concat_many(parts: List["ParsedChanges"]) -> "ParsedChanges":
        parts = [p for p in parts if p.num_changes > 0]
        if not parts:
            return ParsedChanges.empty()
        if len(parts) == 1:
            return parts[0]

        def offsets(key):
            offs = [getattr(parts[0], key)]
            for p in parts[1:]:
                offs.append(getattr(p, key)[1:] + offs[-1][-1])
            return np.concatenate(offs)

        cat = lambda key: np.concatenate([getattr(p, key) for p in parts])  # noqa: E731
        return ParsedChanges(
            ch_actor=cat("ch_actor"),
            ch_seq=cat("ch_seq"),
            dep_off=offsets("dep_off"),
            dep_actor=cat("dep_actor"),
            dep_seq=cat("dep_seq"),
            ops_off=offsets("ops_off"),
            ops=np.concatenate([p.ops for p in parts]),
            cnt_ins=cat("cnt_ins"),
            cnt_del=cat("cnt_del"),
            cnt_mark=cat("cnt_mark"),
            cnt_map=cat("cnt_map"),
        )

    def select(self, indices: np.ndarray) -> "ParsedChanges":
        """Changes at ``indices`` (any order), with deps/ops re-gathered."""
        indices = np.asarray(indices, np.int32)
        dep_idx, dep_off = _ragged_gather(self.dep_off, indices)
        ops_idx, ops_off = _ragged_gather(self.ops_off, indices)
        return ParsedChanges(
            ch_actor=self.ch_actor[indices],
            ch_seq=self.ch_seq[indices],
            dep_off=dep_off,
            dep_actor=self.dep_actor[dep_idx],
            dep_seq=self.dep_seq[dep_idx],
            ops_off=ops_off,
            ops=self.ops[ops_idx],
            cnt_ins=self.cnt_ins[indices],
            cnt_del=self.cnt_del[indices],
            cnt_mark=self.cnt_mark[indices],
            cnt_map=self.cnt_map[indices],
        )


def _ragged_gather(off: np.ndarray, indices: np.ndarray):
    """Element indices for the concatenated ranges off[i]..off[i+1] of the
    selected rows, plus the new offsets array."""
    lens = (off[indices + 1] - off[indices]).astype(np.int64)
    total = int(lens.sum())
    new_off = np.zeros(len(indices) + 1, np.int32)
    np.cumsum(lens, out=new_off[1:])
    if total == 0:
        return np.zeros(0, np.int64), new_off
    starts = off[indices].astype(np.int64)
    base = np.repeat(starts - new_off[:-1], lens)
    return np.arange(total, dtype=np.int64) + base, new_off


class FrameIngestError(Exception):
    """Raised when a frame cannot take the fast path (caller demotes the doc
    to the object path); carries no partial state."""


def parse_frame(
    data: bytes,
    actors: OrderedActorTable,
    attrs: Interner,
    text_obj: int,
    keys: Interner,
) -> Tuple[ParsedChanges, int]:
    """Parse one wire frame into flat arrays on the fast path.

    Returns ``(parsed, text_obj)`` — ``text_obj`` is the packed id of the
    doc's text list, possibly learned from a ``makeList`` in this frame.
    Raises FrameIngestError when the frame needs the object path (native
    core unavailable, JSON-spillover ops other than the initial makeList,
    undeclared actors) and ValueError on corrupt frames.
    """
    from ..parallel.codec import frame_parts

    if not native.available():
        raise FrameIngestError("native core unavailable")
    if len(actors) - 1 > MAX_ACTORS:
        # packed ids collide beyond ACTOR_BITS; the object path demotes the
        # same way (encode.DocEncoder.ok)
        raise FrameIngestError("actor table exceeds packed-id capacity")
    strings, values, n_changes, version = frame_parts(data)
    parsed_raw = native.parse_changes(
        np.asarray(values, np.int32),
        n_changes,
        np.asarray([actors.get(s) if actors.get(s) is not None else -1 for s in strings], np.int32),
        ACTOR_BITS,
        MAX_CTR,
        version=version,
    )
    if parsed_raw is None:  # pragma: no cover - guarded by available() above
        raise FrameIngestError("native core unavailable")
    (ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops,
     cnt_ins, cnt_del, cnt_mark, cnt_map) = parsed_raw

    if np.any(ch_actor < 0):
        raise FrameIngestError("undeclared actor in frame")

    kinds = ops[:, 0]  # NOTE: a view — the JSON->map conversion mutates it
    native_map_rows = np.nonzero(kinds == KIND_MAP)[0]
    # JSON-spillover rows: only the doc's makeList is fast-path-able; it
    # defines the text object and becomes a VK_TEXT map-register row (same
    # conversion as parse_frames_bulk, so text placement competes in register
    # LWW).  A re-delivered copy of the same makeList is idempotent:
    # duplicate frames are a routine anti-entropy condition.
    for row in np.nonzero((kinds == KIND_JSON) | (kinds == KIND_MAKELIST))[0]:
        from .packed import OBJ_ROOT, VK_TEXT

        if kinds[row] == KIND_MAKELIST:
            # wire-v2 native makeList: ids already packed/validated by the
            # native walk (bad ids became KIND_BAD rows, handled below)
            pobj = int(ops[row, 1])
            packed = int(ops[row, 2])
            key = strings[int(ops[row, 3])]
        else:
            try:
                op = Operation.from_json(json.loads(strings[int(ops[row, 3])]))
            except (ValueError, TypeError, KeyError, AttributeError) as exc:
                # same normalized contract as codec.decode_frame
                raise ValueError(f"corrupt frame: {exc!r}") from exc
            if op.action != "makeList" or op.key is None:
                raise FrameIngestError(f"non-text op on fast path: {op.action}")
            actor_idx = actors.get(op.opid[1])
            if actor_idx is None or op.opid[0] > MAX_CTR:
                raise FrameIngestError("makeList opid outside packed range")
            if not isinstance(op.obj, tuple):
                pobj = OBJ_ROOT
            else:
                obj_actor = actors.get(op.obj[1])
                if obj_actor is None or op.obj[0] > MAX_CTR:
                    raise FrameIngestError("makeList container outside packed range")
                pobj = pack_id(op.obj[0], obj_actor)
            packed = pack_id(op.opid[0], actor_idx)
            key = op.key
        if text_obj == 0:
            text_obj = packed
        elif packed != text_obj:
            raise FrameIngestError("second list object on fast path")
        ch = int(np.searchsorted(ops_off, row, side="right")) - 1
        cnt_map[ch] += 1
        ops[row, 0] = KIND_MAP
        ops[row, 1] = pobj
        ops[row, 2] = packed
        ops[row, 3] = keys.intern(key)
        ops[row, 4] = VK_TEXT
        ops[row, 5] = packed
        ops[row, 6:] = 0

    if np.any(kinds == KIND_BAD):
        raise FrameIngestError("op outside packed-id range")

    ins_rows = kinds == KIND_INS
    if np.any(ins_rows):
        cps = ops[ins_rows, 4]
        # same contract as the object path (decode_frame -> chr(cp) raises):
        # an out-of-range codepoint is frame corruption, caught at the door
        # rather than poisoning device state and every later read
        if cps.min(initial=0) < 0 or cps.max(initial=0) > 0x10FFFF:
            raise ValueError("corrupt frame: insert codepoint out of range")

    mark_rows = kinds == KIND_MARK
    if np.any(mark_rows):
        mtypes = ops[mark_rows, 4]
        if mtypes.min(initial=0) < 0 or mtypes.max(initial=0) >= len(ALL_MARKS):
            raise ValueError("mark type index out of range")
        # translate attr string-table indices -> per-doc interned attr ids
        attr_col = ops[:, 9]
        for row in np.nonzero(mark_rows & (attr_col > 0))[0]:
            ops[row, 9] = attrs.intern(strings[int(attr_col[row]) - 1])

    # only NATIVE-emitted map rows carry frame string-table ids; rows the
    # JSON loop converted above are already interned
    if len(native_map_rows):
        from .packed import VK_STR

        for row in native_map_rows:
            ops[row, 3] = keys.intern(strings[int(ops[row, 3])])
            if ops[row, 4] == VK_STR:
                ops[row, 5] = keys.intern(strings[int(ops[row, 5]) - 1])

    parsed = ParsedChanges(
        ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops,
        cnt_ins, cnt_del, cnt_mark, cnt_map,
    )
    return parsed, text_obj


#: parse_frames_bulk per-frame statuses
FRAME_OK = 0
FRAME_CORRUPT = 1  # -> ValueError semantics (nothing ingested)
FRAME_DEMOTE = 2  # -> FrameIngestError semantics (doc leaves the fast path)


def frame_header_counts(buf: np.ndarray, frame_off: np.ndarray):
    """Vectorized header read over concatenated frames: per-frame
    ``(n_changes, n_strings, n_ints)`` clamped by the same sanity rules the
    parser enforces (so corrupt headers cannot inflate allocations), plus a
    per-frame header-valid mask."""
    lens = frame_off[1:] - frame_off[:-1]
    n = len(lens)
    n_changes = np.zeros(n, np.int64)
    n_strings = np.zeros(n, np.int64)
    n_ints = np.zeros(n, np.int64)
    ok = lens >= 29
    if not ok.any():
        return n_changes, n_strings, n_ints, ok
    idx = np.nonzero(ok)[0]
    hdr = buf[np.add.outer(frame_off[:-1][idx], np.arange(29, dtype=np.int64))]
    magic_ok = np.all(hdr[:, :4] == np.frombuffer(b"PTXF", np.uint8), axis=1)
    ver = hdr[:, 4].astype(np.int64)
    ver_ok = (ver == 1) | (ver == 2)
    h_changes = hdr[:, 5:9].copy().view("<u4").ravel().astype(np.int64)
    h_strings = hdr[:, 9:13].copy().view("<u4").ravel().astype(np.int64)
    h_ints = hdr[:, 13:21].copy().view("<u8").ravel().astype(np.int64)
    h_payload = hdr[:, 21:29].copy().view("<u8").ravel().astype(np.int64)
    body = (lens[idx] - 29).astype(np.int64)
    # min ints/change: 5 for v1 headers, 2 for v2's delta-elided form
    min_change_ints = np.where(ver == 1, 5, 2)
    sane = (
        magic_ok & ver_ok
        & (h_payload <= body) & (h_ints <= h_payload) & (h_strings <= body)
        & (h_changes * min_change_ints <= h_ints)
    )
    ok[idx] = sane
    keep = idx[sane]
    n_changes[keep] = h_changes[sane]
    n_strings[keep] = h_strings[sane]
    n_ints[keep] = h_ints[sane]
    return n_changes, n_strings, n_ints, ok


def parse_frames_bulk(
    data: bytes,
    frame_off: np.ndarray,
    actors: OrderedActorTable,
    attrs: Interner,
    doc_ids: np.ndarray,
    text_obj_by_doc: dict,
    keys: Interner | None = None,
):
    """Parse MANY concatenated wire frames in one native call (the bulk twin
    of :func:`parse_frame` — per-frame Python eliminated; SURVEY §5.8's
    pod-scale data loader).

    ``data`` holds the frames back to back with ``frame_off`` (F+1 int64)
    byte offsets; ``doc_ids[f]`` is the document each frame belongs to and
    ``text_obj_by_doc`` maps doc -> packed text-list id (0 = unknown),
    updated in place as makeList ops are consumed.  ``keys`` is the session
    interner for map keys and string values.

    Returns ``(parsed, f_ch_off, status)``: ``parsed`` is one flat
    ParsedChanges across ALL frames (including to-be-demoted ones — slice by
    ``f_ch_off`` and drop by ``status``), statuses per FRAME_* above.
    Returns None when the native core is unavailable.
    """
    if keys is None:
        keys = Interner()
    if not native.available():
        return None
    if len(actors) - 1 > MAX_ACTORS:
        n_frames = len(frame_off) - 1
        return (
            ParsedChanges.empty(),
            np.zeros(n_frames + 1, np.int32),
            np.full(n_frames, FRAME_DEMOTE, np.int32),
        )
    buf = np.frombuffer(data, np.uint8)
    n_frames = len(frame_off) - 1
    actor_strings = [actors.lookup(i) for i in range(1, len(actors))]

    # Broadcast fan-out dedup (round 5, VERDICT r4 task 3): a change
    # broadcast to many docs arrives as byte-identical frames (the scale
    # demo ships ONE session to 100K docs), and the varint parse is pure in
    # the frame bytes — doc-specific logic (makeList adoption, comment-id
    # interning, demotion) all runs AFTER the native call in this wrapper.
    # So identical frames parse once and the raw parse replicates with
    # numpy gathers; replicated op rows are real copies (the per-doc
    # comment remap mutates them), while the string TABLE is shared
    # (global ids point into the unique frames' bytes).
    # cheap pre-screen: every duplicate shares a byte length, so more than
    # n/2 distinct lengths rules dedup out without touching frame bytes —
    # the all-unique pod-scale case pays O(F) ints, not O(wire bytes)
    f_lens = np.diff(frame_off)
    dedup = n_frames > 1 and len(np.unique(f_lens)) <= n_frames // 2
    if dedup:
        uniq_index: dict = {}
        inv = np.empty(n_frames, np.int64)
        uniq_frames: list = []
        for i in range(n_frames):
            fb = data[frame_off[i]:frame_off[i + 1]]
            j = uniq_index.setdefault(fb, len(uniq_frames))
            if j == len(uniq_frames):
                uniq_frames.append(fb)
            inv[i] = j
        dedup = len(uniq_frames) <= n_frames // 2

    if dedup:
        s_bytes = b"".join(uniq_frames)
        u_buf = s_buf = np.frombuffer(s_bytes, np.uint8)
        u_off = np.concatenate(
            [[0], np.cumsum([len(f) for f in uniq_frames], dtype=np.int64)]
        ).astype(np.int64)
        n_changes, n_strings, n_ints, u_hdr_ok = frame_header_counts(u_buf, u_off)
        out = native.parse_frames(
            u_buf, u_off,
            (int(n_changes.sum()), int(n_strings.sum()), int(n_ints.sum())),
            actor_strings, ACTOR_BITS, MAX_CTR,
        )
        if out is None:  # pragma: no cover - available() checked above
            return None
        (u_f_status, u_f_ch_off, _u_f_str_off, str_start, str_len,
         u_ch_actor, u_ch_seq, u_dep_off, u_dep_actor, u_dep_seq,
         u_ops_off, u_ops, u_ci, u_cd, u_cm, u_cp) = out

        # replicate per original frame (then per change) by expanding each
        # unique slice — _ragged_gather handles empty selections (a batch
        # of duplicated zero-change/corrupt frames must reach the normal
        # corrupt-frame handling, not a numpy broadcast error)
        ch_src, f_ch_off = _ragged_gather(u_f_ch_off, inv)
        ch_actor = u_ch_actor[ch_src]
        ch_seq = u_ch_seq[ch_src]
        cnt_ins, cnt_del = u_ci[ch_src], u_cd[ch_src]
        cnt_mark, cnt_map = u_cm[ch_src], u_cp[ch_src]
        dep_src, dep_off = _ragged_gather(u_dep_off, ch_src)
        dep_actor = u_dep_actor[dep_src]
        dep_seq = u_dep_seq[dep_src]
        ops_src, ops_off = _ragged_gather(u_ops_off, ch_src)
        ops = u_ops[ops_src]  # fancy indexing: already a fresh per-replica copy
        f_status = u_f_status[inv]
        hdr_ok = u_hdr_ok[inv]
    else:
        s_bytes, s_buf = data, buf
        n_changes, n_strings, n_ints, hdr_ok = frame_header_counts(buf, frame_off)
        out = native.parse_frames(
            buf,
            frame_off,
            (int(n_changes.sum()), int(n_strings.sum()), int(n_ints.sum())),
            actor_strings,
            ACTOR_BITS,
            MAX_CTR,
        )
        if out is None:  # pragma: no cover - available() checked above
            return None
        (f_status, f_ch_off, f_str_off, str_start, str_len,
         ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops,
         cnt_ins, cnt_del, cnt_mark, cnt_map) = out
    status = f_status.astype(np.int32)
    kinds = ops[:, 0]  # NOTE: a view — JSON->map conversion below mutates it
    native_map_rows = np.nonzero(kinds == KIND_MAP)[0]

    def frames_of_ops(rows: np.ndarray) -> np.ndarray:
        changes = np.searchsorted(ops_off, rows, side="right") - 1
        return (np.searchsorted(f_ch_off, changes, side="right") - 1).astype(np.int64)

    # Byte-content string access: slices of the original bytes object (no
    # numpy round trip), decoded once per distinct content.
    _decoded: dict = {}

    def string_at(gid: int) -> str:
        # s_bytes: the buffer str_start indexes — the unique-frame concat
        # under dedup, the original data otherwise
        start = int(str_start[gid])
        raw = s_bytes[start : start + int(str_len[gid])]
        s = _decoded.get(raw)
        if s is None:
            s = raw.decode("utf-8")
            _decoded[raw] = s
        return s

    # Validation passes run BEFORE the makeList adoption below, so a frame
    # that will be rejected can never leak state into text_obj_by_doc.
    # Value validation first (corrupt-frame semantics, as in parse_frame):
    ins_bad = (kinds == KIND_INS) & ((ops[:, 4] < 0) | (ops[:, 4] > 0x10FFFF))
    mark_bad = (kinds == KIND_MARK) & (
        (ops[:, 4] < 0) | (ops[:, 4] >= len(ALL_MARKS))
    )
    value_bad = np.nonzero(ins_bad | mark_bad)[0]
    if len(value_bad):
        status[frames_of_ops(value_bad)] = FRAME_CORRUPT
    status[~hdr_ok] = FRAME_CORRUPT  # belt: native flags these too

    # Undeclared actors / out-of-range ids (KIND_BAD) demote their frame.
    bad_rows = np.nonzero(kinds == KIND_BAD)[0]
    if len(bad_rows):
        for f in np.unique(frames_of_ops(bad_rows)):
            if status[f] == FRAME_OK:
                status[f] = FRAME_DEMOTE
    if (ch_actor < 0).any():
        ch_frame = np.repeat(np.arange(n_frames), np.diff(f_ch_off))
        for f in np.unique(ch_frame[ch_actor < 0]):
            if status[f] == FRAME_OK:
                status[f] = FRAME_DEMOTE

    # Session-level string interning (mark attrs, map keys, map string
    # values).  Unique by byte CONTENT, not by global string id: every frame
    # carries its own string table, so the same url / key reappears under
    # thousands of distinct gids at pod scale.  Fully vectorized — group by
    # length, gather an (N, len) byte matrix, np.unique rows, decode only
    # the handful of distinct strings.
    def intern_column(rows: np.ndarray, col: int, offset: int, table: Interner):
        """Rewrite ``ops[rows, col]`` (global strid + offset) to interned
        ids; flags frames of undecodable strings corrupt."""
        all_gids = ops[rows, col] - offset
        # unique-gid indirection: replicated broadcast frames (and any
        # repeated attr within a session) share gids, so byte gathering
        # and decoding run once per DISTINCT string id, not per op row —
        # at 32K docs this was ~2 s of redundant (N, len) gathers (r5)
        gids, gid_inv = np.unique(all_gids, return_inverse=True)
        starts = str_start[gids]
        lens = str_len[gids]
        new_ids = np.zeros(len(gids), np.int32)
        bad_mask = np.zeros(len(gids), bool)
        for ln in np.unique(lens):
            sel = np.nonzero(lens == ln)[0]
            if ln == 0:
                new_ids[sel] = table.intern("")
                continue
            content = s_buf[starts[sel][:, None] + np.arange(int(ln), dtype=np.int64)]
            uniq_rows, inv = np.unique(content, axis=0, return_inverse=True)
            ids = np.empty(len(uniq_rows), np.int32)
            for j in range(len(uniq_rows)):
                try:
                    ids[j] = table.intern(uniq_rows[j].tobytes().decode("utf-8"))
                except UnicodeDecodeError:
                    ids[j] = -1  # decode failure: corrupt-frame semantics
            mapped = ids[inv]
            bad_mask[sel] = mapped < 0
            new_ids[sel] = np.maximum(mapped, 0)
        row_bad = bad_mask[gid_inv]
        if row_bad.any():
            status[frames_of_ops(rows[row_bad])] = FRAME_CORRUPT
        ops[rows, col] = new_ids[gid_inv]

    attr_rows = np.nonzero((kinds == KIND_MARK) & (ops[:, 9] > 0))[0]
    if len(attr_rows):
        intern_column(attr_rows, col=9, offset=1, table=attrs)
    # only rows the NATIVE parser emitted carry global string ids; rows the
    # JSON loop below converts are interned as they are rewritten
    if len(native_map_rows):
        from .packed import VK_STR

        intern_column(native_map_rows, col=3, offset=0, table=keys)
        str_val_rows = native_map_rows[ops[native_map_rows, 4] == VK_STR]
        if len(str_val_rows):
            intern_column(str_val_rows, col=5, offset=1, table=keys)

    # JSON-spillover rows: only each doc's makeList is fast-path-able (same
    # contract as parse_frame).  Frames are processed in arrival order so a
    # makeList learned from an earlier frame governs later frames of the same
    # doc — but each frame's adoption commits only if the whole frame stays
    # OK (a frame that fails mid-way must contribute nothing).  This loop
    # runs AFTER the string-interning passes above so a frame they flag
    # FRAME_CORRUPT (undecodable mark-attr / map-key bytes) is skipped here
    # and can never leak a makeList adoption into text_obj_by_doc
    # (advisor finding r2: a crafted corrupt frame could otherwise poison a
    # doc's text object and demote all its later valid text ops).
    json_rows = np.nonzero((kinds == KIND_JSON) | (kinds == KIND_MAKELIST))[0]
    if len(json_rows):
        from .packed import OBJ_ROOT, VK_TEXT

        jr_frames = frames_of_ops(json_rows)
        # change index of every json row, vectorized once (a per-row
        # searchsorted over a 20M-entry ops_off would dominate at pod scale)
        jr_chs = np.searchsorted(ops_off, json_rows, side="right") - 1
        ch_of_row = dict(zip(json_rows.tolist(), jr_chs.tolist()))
        # group rows per frame ONCE (a per-frame boolean scan would be
        # quadratic at 100K frames/call)
        order = np.argsort(jr_frames, kind="stable")
        sorted_frames = jr_frames[order]
        grp_starts = np.nonzero(
            np.concatenate([[True], sorted_frames[1:] != sorted_frames[:-1]])
        )[0]
        grp_ends = np.append(grp_starts[1:], len(order))
        for gs, ge in zip(grp_starts.tolist(), grp_ends.tolist()):
            f = int(sorted_frames[gs])
            if status[f]:
                continue
            doc = int(doc_ids[f])
            local_text = text_obj_by_doc.get(doc, 0)
            staged: list = []
            for row in json_rows[order[gs:ge]]:
                if kinds[row] == KIND_MAKELIST:
                    # wire-v2 native makeList: ids already packed/validated
                    # (bad ids became KIND_BAD rows, which demote the frame
                    # before this loop runs)
                    pobj, packed = int(ops[row, 1]), int(ops[row, 2])
                    try:
                        key = string_at(int(ops[row, 3]))
                    except UnicodeDecodeError:
                        status[f] = FRAME_CORRUPT
                        break
                else:
                    try:
                        op = Operation.from_json(json.loads(string_at(int(ops[row, 3]))))
                    except (ValueError, TypeError, KeyError, AttributeError,
                            UnicodeDecodeError):
                        status[f] = FRAME_CORRUPT
                        break
                    if op.action != "makeList" or op.key is None:
                        status[f] = FRAME_DEMOTE
                        break
                    actor_idx = actors.get(op.opid[1])
                    if actor_idx is None or op.opid[0] > MAX_CTR:
                        status[f] = FRAME_DEMOTE
                        break
                    if not isinstance(op.obj, tuple):
                        pobj = OBJ_ROOT  # the ROOT sentinel (or absent) = root map
                    else:
                        obj_actor = actors.get(op.obj[1])
                        if obj_actor is None or op.obj[0] > MAX_CTR:
                            status[f] = FRAME_DEMOTE
                            break
                        pobj = pack_id(op.obj[0], obj_actor)
                    packed = pack_id(op.opid[0], actor_idx)
                    key = op.key
                if local_text == 0:
                    local_text = packed
                elif packed != local_text:
                    status[f] = FRAME_DEMOTE
                    break
                staged.append((row, pobj, packed, key))
            if status[f] == FRAME_OK and staged:
                text_obj_by_doc[doc] = local_text
                # Rewrite the spillover row into a VK_TEXT map-register row:
                # the text list placement then competes in register LWW like
                # any other key (the object path emits the same register),
                # instead of being host-injected at read time.
                for row, pobj, packed, key in staged:
                    cnt_map[ch_of_row[int(row)]] += 1
                    ops[row, 0] = KIND_MAP
                    ops[row, 1] = pobj
                    ops[row, 2] = packed
                    ops[row, 3] = keys.intern(key)
                    ops[row, 4] = VK_TEXT
                    ops[row, 5] = packed
                    ops[row, 6:] = 0

    parsed = ParsedChanges(
        ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops,
        cnt_ins, cnt_del, cnt_mark, cnt_map,
    )
    return parsed, f_ch_off, status


def _py_schedule_order(
    parsed: ParsedChanges, n_actors: int, clock: np.ndarray
) -> np.ndarray:
    """Pure-python twin of native causal_schedule_indices (fallback only)."""
    n = parsed.num_changes
    clock = clock.copy()
    remaining = sorted(range(n), key=lambda i: (parsed.ch_actor[i], parsed.ch_seq[i]))
    order: List[int] = []
    progress = True
    done = np.zeros(n, bool)
    while progress:
        progress = False
        for i in remaining:
            if done[i]:
                continue
            a, s = int(parsed.ch_actor[i]), int(parsed.ch_seq[i])
            if s <= clock[a]:
                done[i] = True  # stale duplicate
                continue
            if s != clock[a] + 1:
                continue
            deps = range(parsed.dep_off[i], parsed.dep_off[i + 1])
            if any(clock[parsed.dep_actor[d]] < parsed.dep_seq[d] for d in deps):
                continue
            clock[a] = s
            done[i] = True
            order.append(i)
            progress = True
    return np.asarray(order, np.int32)


def schedule_split(
    parsed: ParsedChanges,
    clock: np.ndarray,
    text_obj: int,
    caps: Tuple[int, int, int, int],
    out_ins: Tuple[np.ndarray, np.ndarray, np.ndarray],
    out_del: np.ndarray,
    out_marks: dict,
    out_maps: dict,
    n_actors: int,
) -> Tuple[int, Tuple[int, int, int, int], ParsedChanges]:
    """One round: admit the longest causally-valid prefix that fits the
    static stream widths, split its ops into the caller's padded row views,
    and advance ``clock`` in place.

    Returns ``(changes_admitted, (n_ins, n_del, n_mark, n_map), deferred)``.
    Raises FrameIngestError if an admitted list op targets an object other
    than the doc's text list (the caller demotes the doc); map-register ops
    (KIND_MAP) may target any map object.
    """
    n = parsed.num_changes
    if n == 0:
        return 0, (0, 0, 0, 0), parsed
    ki, kd, km, kp = caps

    stale = parsed.ch_seq <= clock[parsed.ch_actor]
    order = native.causal_schedule_indices(
        parsed.ch_actor, parsed.ch_seq, parsed.dep_off,
        parsed.dep_actor, parsed.dep_seq, n_actors, clock,
    )
    if order is None:
        order = _py_schedule_order(parsed, n_actors, clock)

    # Budget: longest schedulable prefix fitting every stream width.
    fits = (
        (np.cumsum(parsed.cnt_ins[order]) <= ki)
        & (np.cumsum(parsed.cnt_del[order]) <= kd)
        & (np.cumsum(parsed.cnt_mark[order]) <= km)
        & (np.cumsum(parsed.cnt_map[order]) <= kp)
    )
    cut = int(np.argmax(~fits)) if not fits.all() else len(order)
    if cut == 0 and len(order) > 0:
        # The first admissible change alone exceeds a round width: it can
        # never fit, so deferring would wedge the doc forever — demote it.
        raise FrameIngestError("a single change exceeds the round stream widths")
    admitted = order[:cut]
    if len(admitted) == 0:
        return 0, (0, 0, 0, 0), parsed.select(np.nonzero(~stale)[0])

    ops_idx, _ = _ragged_gather(parsed.ops_off, admitted)
    sel = parsed.ops[ops_idx]
    kinds = sel[:, 0]
    live = (kinds != KIND_SKIP) & (kinds != KIND_MAP)
    if not np.all((sel[:, 1][live] == text_obj)):
        raise FrameIngestError("op on non-text object on fast path")
    # a map op whose CONTAINER is the text list is malformed (the oracle
    # raises on it); demote rather than diverge
    map_kind = kinds == KIND_MAP
    if text_obj != 0 and np.any(map_kind & (sel[:, 1] == text_obj)):
        raise FrameIngestError("map op targeting the text list")

    ins = sel[kinds == KIND_INS]
    dels = sel[kinds == KIND_DEL]
    marks = sel[kinds == KIND_MARK]
    maps = sel[kinds == KIND_MAP]
    ni, nd, nm, np_ = len(ins), len(dels), len(marks), len(maps)
    ins_ref, ins_op, ins_char = out_ins
    ins_ref[:ni] = ins[:, 3]
    ins_op[:ni] = ins[:, 2]
    ins_char[:ni] = ins[:, 4]
    out_del[:nd] = dels[:, 3]
    for col_name, col in zip(
        ("m_action", "m_type", "m_start_kind", "m_start_elem",
         "m_end_kind", "m_end_elem", "m_op", "m_attr"),
        _MARK_COL_ORDER,
    ):
        out_marks[col_name][:nm] = marks[:, col]
    for col_name, col in zip(
        ("p_obj", "p_key", "p_op", "p_kind", "p_val"), (1, 3, 2, 4, 5)
    ):
        out_maps[col_name][:np_] = maps[:, col]

    np.maximum.at(clock, parsed.ch_actor[admitted], parsed.ch_seq[admitted])

    admitted_mask = np.zeros(n, bool)
    admitted_mask[admitted] = True
    deferred = parsed.select(np.nonzero(~admitted_mask & ~stale)[0])
    return len(admitted), (ni, nd, nm, np_), deferred
