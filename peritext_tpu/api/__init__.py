"""Placeholder package init; populated by subsequent milestones."""
