"""User-facing facades: batched merge backend and (scalar) document API."""

from .batch import DocBatch, MergeReport, Workload, oracle_merge

__all__ = ["DocBatch", "MergeReport", "Workload", "oracle_merge"]
