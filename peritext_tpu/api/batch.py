"""DocBatch: the batched TPU merge backend.

The user-facing entry for the framework's north-star workload: given change
logs for D collaborative documents (each a dict actor -> [Change], exactly
what the replication layer accumulates), converge all of them at once on
device and return each document's formatted spans.

Pipeline: host causal sort + interning + stream splitting (ops/encode.py) ->
device batched apply (ops/kernel.py) -> device span resolution
(ops/resolve.py) -> host decode (ops/decode.py).  Documents the device path
cannot express (non-text objects, too many actors) or that overflow their
static capacities fall back to the scalar oracle (core/doc.py) transparently;
``MergeReport.fallback_docs`` says which.

Semantically equivalent to constructing a fresh ``core.Doc`` per workload and
replaying all changes — the differential tests assert exactly that equality.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.doc import Doc
from ..core.types import Change, FormatSpan
from ..obs import (
    GLOBAL_COUNTERS,
    GLOBAL_DEVPROF,
    GLOBAL_HISTOGRAMS,
    GLOBAL_TRACER,
    MergeStats,
    occupancy_key,
)
from ..ops.decode import decode_block_spans
from ..ops.encode import EncodedBatch, encode_workloads
from ..ops.kernel import apply_batch, apply_batch_jit, encoded_arrays_of
from ..ops.packed import PackedDocs, empty_docs
from ..ops.resolve import resolve, resolve_jit
from ..parallel.causal import causal_sort

Workload = Dict[str, List[Change]]


@dataclass
class MergeReport:
    """Outcome of a batched merge."""

    spans: List[List[FormatSpan]]
    #: doc indices resolved by the scalar oracle instead of the device
    fallback_docs: List[int] = field(default_factory=list)
    #: ops applied on device (excludes fallback docs)
    device_ops: int = 0
    #: per-merge observability (stage timings, padding efficiency)
    stats: MergeStats = field(default_factory=MergeStats)
    #: resolved cursor indices (aligned with merge()'s ``cursors`` argument);
    #: -1 = cursor's element does not exist in the converged document
    cursor_positions: Optional[List[List[int]]] = None
    #: per-doc materialized root map (nested maps + text list), equal to the
    #: scalar oracle's ``Doc.root`` — device docs decode their LWW register
    #: table (ops/decode.decode_doc_root), fallback docs replay
    roots: Optional[List[dict]] = None


class DocBatch:
    """Batched document merge engine.

    Capacities are static (XLA compiles one program per shape bucket):
    ``slot_capacity`` bounds elements-including-tombstones per doc,
    ``mark_capacity`` bounds mark ops per doc, ``comment_capacity`` bounds
    distinct interned attrs per doc, ``op_capacity`` bounds the insert and
    delete streams per merge call (None = sized to the batch).
    """

    def __init__(
        self,
        slot_capacity: int = 256,
        mark_capacity: int = 64,
        comment_capacity: int = 32,
        op_capacity: Optional[int] = None,
        map_capacity: int = 32,
        jit: bool = True,
        mesh=None,
        guard: bool = False,
        tracer=None,
        layout: str = "padded",
        page_size: Optional[int] = None,
    ) -> None:
        #: storage layout: "padded" (one (D, S) batch, every doc at the
        #: widest bucket — the byte-equality oracle), "paged" (store/
        #: page pool + per-doc page tables; docs group by size bucket so
        #: stream padding AND element-plane memory scale with real ops),
        #: or "ragged" (same pool, but ONE apply over every doc's true op
        #: and page counts — no bucket ladder, one compiled program; see
        #: ops/ragged.py).
        if layout not in ("padded", "paged", "ragged"):
            raise ValueError(f"unknown layout: {layout!r}")
        if layout in ("paged", "ragged") and mesh is not None:
            raise ValueError(
                f"layout={layout!r} does not support a mesh yet"
            )
        self.layout = layout
        if page_size is None:
            from ..store import DEFAULT_PAGE_SIZE

            page_size = DEFAULT_PAGE_SIZE
        self.page_size = int(page_size)
        if layout in ("paged", "ragged") and slot_capacity % self.page_size:
            raise ValueError(
                f"slot_capacity {slot_capacity} must be a multiple of "
                f"page_size {self.page_size} under layout={layout!r}"
            )
        #: pipeline-span producer (obs/spans.py): merge() opens a
        #: ``batch.merge`` span with encode/apply/resolve/decode children,
        #: whose durations also feed MergeStats — one clock, two surfaces
        self.tracer = tracer if tracer is not None else GLOBAL_TRACER
        self.slot_capacity = slot_capacity
        self.mark_capacity = mark_capacity
        self.comment_capacity = comment_capacity
        self.op_capacity = op_capacity
        self.map_capacity = map_capacity
        #: fault-domain guard: a device-stage failure (XLA compile/runtime
        #: error, device OOM) degrades the whole merge to the scalar oracle
        #: — slower but byte-identical — instead of raising.  Off by default
        #: so development surfaces device bugs loudly; the supervisor layer
        #: turns it on for production serving.
        self.guard = guard
        #: optional jax.sharding.Mesh; when set, the doc axis of every tensor
        #: is sharded across it (pure data parallelism; XLA adds collectives
        #: only for cross-doc reductions like the convergence digest).
        self.mesh = mesh
        # Reuse the module-level jitted wrappers: JAX's compilation cache is
        # keyed per-wrapper, so per-instance jax.jit would recompile the same
        # kernel for every DocBatch.
        self._apply = apply_batch_jit if jit else apply_batch
        self._resolve = resolve_jit if jit else resolve
        #: the page store of the most recent paged merge (telemetry/tests)
        self.last_store = None

    # -- device pipeline ---------------------------------------------------

    def encode(self, workloads: Sequence[Workload]) -> EncodedBatch:
        return encode_workloads(
            list(workloads),
            insert_capacity=self.op_capacity,
            delete_capacity=self.op_capacity,
            mark_capacity=self.mark_capacity,
        )

    def apply_encoded(self, encoded: EncodedBatch) -> PackedDocs:
        """Run the batched two-phase apply on an encoded batch."""
        arrays = encoded_arrays_of(encoded)
        num_docs = encoded.num_docs
        if self.mesh is not None:
            from ..parallel.mesh import pad_doc_axis, shard_docs
            import jax

            arrays = jax.tree_util.tree_map(
                lambda x: pad_doc_axis(np.asarray(x), self.mesh.size), arrays
            )
            arrays = shard_docs(arrays, self.mesh)
            num_docs = arrays[0].shape[0]
        state = empty_docs(
            num_docs,
            self.slot_capacity,
            self.mark_capacity,
            tomb_capacity=arrays[3].shape[1],  # delete-stream width
            map_capacity=self.map_capacity,
        )
        if self.mesh is not None:
            from ..parallel.mesh import shard_docs

            state = shard_docs(state, self.mesh)
        return self._apply(state, arrays)

    def merge(
        self,
        workloads: Sequence[Workload],
        cursors: Optional[Sequence[Sequence[dict]]] = None,
    ) -> MergeReport:
        """Converge every workload; returns per-doc formatted spans.

        ``cursors`` optionally gives, per document, stable cursors
        (``{"objectId", "elemId"}``, the reference's ``Cursor`` shape,
        src/micromerge.ts:859-870) to resolve against the converged state;
        resolved visible indices land in ``MergeReport.cursor_positions``
        (-1 when the cursor's element is absent).  Device docs resolve on
        device (ops/resolve.resolve_cursors); fallback docs via the oracle.
        """
        with self.tracer.span("batch.merge", docs=len(workloads)) as sp:
            if self.layout == "paged":
                report = self._merge_paged(workloads, cursors)
            elif self.layout == "ragged":
                report = self._merge_ragged(workloads, cursors)
            else:
                report = self._merge(workloads, cursors)
        GLOBAL_HISTOGRAMS.observe("merge.seconds", sp.duration)
        return report

    def _merge(
        self,
        workloads: Sequence[Workload],
        cursors: Optional[Sequence[Sequence[dict]]],
    ) -> MergeReport:
        """merge() behind its pipeline span: each stage runs under a child
        span whose duration doubles as the MergeStats stage wall-clock."""
        stats = MergeStats(docs=len(workloads))
        with self.tracer.span("batch.encode") as sp:
            encoded = self.encode(workloads)
        stats.encode_seconds = sp.duration

        try:
            with self.tracer.span("batch.apply") as sp:
                state = self.apply_encoded(encoded)
                np.asarray(state.num_slots)  # host sync: time apply honestly
            stats.apply_seconds = sp.duration

            with self.tracer.span("batch.resolve") as sp:
                resolved_dev = self._resolve(state, self.comment_capacity)
                # One whole-array transfer per field, up front: decoding per
                # doc on the raw (possibly mesh-sharded) arrays would do 5
                # device gathers per document.
                resolved = type(resolved_dev)(
                    *(np.asarray(x) for x in resolved_dev)
                )
            stats.resolve_seconds = sp.duration
        except Exception as exc:  # graftlint: boundary(guarded merge: ANY device-path failure degrades to the scalar oracle; re-raised when unguarded)
            if not self.guard:
                raise
            return self._degraded_merge(workloads, cursors, stats, exc)

        overflow = np.asarray(resolved.overflow)
        fallback = set(encoded.fallback_docs) | {
            int(d) for d in np.nonzero(overflow)[0] if d < len(workloads)
        }

        # Fallback docs may be replayed for both cursors and spans; build each
        # oracle doc at most once per merge.
        oracle_docs: Dict[int, Doc] = {}

        def oracle_doc_for(d: int) -> Doc:
            if d not in oracle_docs:
                oracle_docs[d] = _oracle_doc(workloads[d])
            return oracle_docs[d]

        cursor_positions: Optional[List[List[int]]] = None
        if cursors is not None:
            cursor_positions = self._resolve_cursor_batch(
                state, resolved_dev.visible, encoded, cursors, fallback, oracle_doc_for
            )

        with self.tracer.span("batch.decode") as sp:
            from ..ops.decode import decode_doc_root
            from types import SimpleNamespace

            # register table transfer (small: 5 x (D, R) int32)
            regs = SimpleNamespace(
                r_obj=np.asarray(state.r_obj), r_key=np.asarray(state.r_key),
                r_op=np.asarray(state.r_op), r_kind=np.asarray(state.r_kind),
                r_val=np.asarray(state.r_val), num_regs=np.asarray(state.num_regs),
            )
            # one vectorized span decode for the whole batch (Python touches
            # only mark-run segments); fallback docs replay through the oracle
            device_mask = np.zeros(resolved.visible.shape[0], bool)
            for d in range(len(workloads)):
                device_mask[d] = d not in fallback
            block_spans = decode_block_spans(
                resolved,
                lambda d: encoded.attr_tables[d],
                lambda d: encoded.attr_tables[d],
                doc_mask=device_mask,
            )
            spans: List[List[FormatSpan]] = []
            roots: List[dict] = []
            device_ops = 0
            fallback_ops = 0
            for d, workload in enumerate(workloads):
                if d in fallback:
                    doc = oracle_doc_for(d)
                    spans.append(doc.get_text_with_formatting(["text"]))
                    roots.append(doc.root)
                    fallback_ops += int(encoded.num_ops[d])
                else:
                    spans.append(block_spans[d])
                    roots.append(
                        decode_doc_root(regs, resolved, d, encoded.map_tables[d])
                    )
                    device_ops += int(encoded.num_ops[d])
        stats.decode_seconds = sp.duration

        stream_capacity = encoded.num_docs * (
            encoded.ins_op.shape[1]
            + encoded.del_target.shape[1]
            + next(iter(encoded.marks.values())).shape[1]
            + next(iter(encoded.map_ops.values())).shape[1]
        )
        stats.device_ops = device_ops
        stats.fallback_ops = fallback_ops
        stats.fallback_docs = len(fallback)
        stats.device_docs = len(workloads) - len(fallback)
        stats.padding_efficiency = (
            float(encoded.num_ops.sum()) / stream_capacity if stream_capacity else 0.0
        )
        if GLOBAL_DEVPROF.enabled:
            # one-shot batch merges land in the same bucket-occupancy table
            # as streaming rounds, keyed by their padded stream widths
            GLOBAL_DEVPROF.observe_round(
                occupancy_key(
                    encoded.num_docs,
                    encoded.ins_op.shape[1],
                    encoded.del_target.shape[1],
                    next(iter(encoded.marks.values())).shape[1],
                    next(iter(encoded.map_ops.values())).shape[1],
                ),
                int(encoded.num_ops.sum()), stream_capacity,
                origin="batch.merge",
            )
            GLOBAL_DEVPROF.sample_memory()
        GLOBAL_COUNTERS.add("merge.calls")
        GLOBAL_COUNTERS.add("merge.device_ops", device_ops)
        GLOBAL_COUNTERS.add("merge.fallback_docs", len(fallback))
        return MergeReport(
            spans=spans,
            fallback_docs=sorted(fallback),
            device_ops=device_ops,
            stats=stats,
            cursor_positions=cursor_positions,
            roots=roots,
        )

    # -- paged layout (store/) ----------------------------------------------

    def _merge_paged(
        self,
        workloads: Sequence[Workload],
        cursors: Optional[Sequence[Sequence[dict]]],
    ) -> MergeReport:
        """merge() under ``layout="paged"`` (store/paged.py): docs group
        into power-of-two page-count buckets; each bucket encodes, applies
        and resolves at ITS OWN widths through the page pool's gather-based
        apply (ops/kernel.apply_batch_paged), so stream padding and
        element-plane memory scale with real ops instead of every doc
        paying the widest doc's bucket.  The padded path is the
        byte-equality oracle — the differential tests pin spans / roots /
        cursors equality across both layouts on every fuzz seed."""
        from types import SimpleNamespace

        from ..ops.decode import decode_block_spans, decode_doc_root
        from ..ops.encode import _EMPTY_STREAMS, encode_doc_streams, pad_doc_streams
        from ..store.paged import (
            PagedDocStore,
            _pow2,
            group_stream_arrays,
        )

        stats = MergeStats(docs=len(workloads))
        d_total = len(workloads)
        with self.tracer.span("batch.encode") as sp:
            per_doc, fb_encode, actor_tables, attr_tables, map_tables = (
                encode_doc_streams(workloads)
            )
            fb_set = set(fb_encode)
            # capacity fallback happens HERE, not in pad_doc_streams: group
            # streams size to the subgroup max (that is the point of the
            # layout), so the configured capacities act as per-doc fallback
            # thresholds exactly as they do on the padded path — same docs
            # fall back under both layouts
            empty = _EMPTY_STREAMS
            for d in range(d_total):
                s = per_doc[d]
                over = len(s.marks) > self.mark_capacity
                if self.op_capacity is not None:
                    over = over or len(s.ins) > self.op_capacity \
                        or len(s.dels) > self.op_capacity
                if over:
                    fb_set.add(d)
            # two-component size bucketing: page need (inserts drive slots —
            # the delete/mark/register tables stay dense aux rows) AND a
            # power-of-two total-op bucket.  The second component matters
            # below one page: without it every sub-page tweet pads its
            # streams to the widest tweet's op count, which is most of the
            # long-tail waste the paged layout exists to kill.  Fallback
            # docs carry no streams and ride the smallest bucket as no-ops.
            max_pages = max(1, self.slot_capacity // self.page_size)
            buckets: Dict[tuple, List[int]] = {}
            for d in range(d_total):
                s = empty if d in fb_set else per_doc[d]
                ops = len(s.ins) + len(s.dels) + len(s.marks) + len(s.maps)
                g = min(
                    _pow2(-(-max(1, len(s.ins)) // self.page_size)), max_pages
                )
                buckets.setdefault((g, _pow2(max(8, ops))), []).append(d)
            groups = [(g, np.asarray(buckets[(g, sb)], np.int64))
                      for g, sb in sorted(buckets)]
            encs = []
            for g, docs in groups:
                local_fb = [i for i, d in enumerate(docs) if int(d) in fb_set]
                enc_g = pad_doc_streams(
                    [empty if int(d) in fb_set else per_doc[int(d)]
                     for d in docs],
                    local_fb,
                    [actor_tables[int(d)] for d in docs],
                    [attr_tables[int(d)] for d in docs],
                    map_tables=[map_tables[int(d)] for d in docs],
                )
                encs.append((g, docs, enc_g))
        stats.encode_seconds = sp.duration

        try:
            with self.tracer.span("batch.apply") as sp:
                tomb_cap = max(
                    (enc.del_target.shape[1] for _, _, enc in encs), default=8
                )
                store = PagedDocStore(
                    d_total,
                    slot_capacity=self.slot_capacity,
                    mark_capacity=self.mark_capacity,
                    tomb_capacity=tomb_cap,
                    map_capacity=self.map_capacity,
                    page_size=self.page_size,
                )
                self.last_store = store
                stream_capacity = 0
                real_ops = 0
                for g, docs, enc in encs:
                    ins_counts = (np.asarray(enc.ins_op) != 0).sum(axis=1)
                    store.ensure_rows(docs, ins_counts)
                    b = _pow2(len(docs))
                    store.apply_rows(
                        docs, g, group_stream_arrays(enc, None, b),
                        pad_rows_to=b,
                    )
                    widths = (
                        enc.ins_op.shape[1], enc.del_target.shape[1],
                        next(iter(enc.marks.values())).shape[1],
                        next(iter(enc.map_ops.values())).shape[1],
                    )
                    # capacity is what the DISPATCHED program paid: b padded
                    # rows, not the real group size — the streaming paged
                    # path and the occupancy table must agree on this
                    group_cap = b * sum(widths)
                    stream_capacity += group_cap
                    real_ops += int(enc.num_ops.sum())
                    if GLOBAL_DEVPROF.enabled:
                        GLOBAL_DEVPROF.observe_round(
                            occupancy_key(b, *widths),
                            int(enc.num_ops.sum()), group_cap,
                            origin="batch.merge.paged",
                        )
                # host sync: time apply honestly (mirror of _merge)
                np.asarray(store.aux_field("num_slots"))
            stats.apply_seconds = sp.duration

            with self.tracer.span("batch.resolve") as sp:
                resolved_groups = []
                for g, docs, enc in encs:
                    # same power-of-two row bucket as the apply: gather,
                    # resolve and cursor programs compile once per
                    # (rows-bucket, pages-bucket, widths), never per exact
                    # group size; padding rows are masked downstream
                    b = _pow2(len(docs))
                    state_g = store.materialize_rows(docs, g, pad_rows_to=b)
                    res_dev = self._resolve(state_g, self.comment_capacity)
                    res_np = type(res_dev)(*(np.asarray(x) for x in res_dev))
                    resolved_groups.append((docs, enc, state_g, res_dev, res_np))
            stats.resolve_seconds = sp.duration
        except Exception as exc:  # graftlint: boundary(guarded merge: ANY device-path failure degrades to the scalar oracle; re-raised when unguarded)
            if not self.guard:
                raise
            return self._degraded_merge(workloads, cursors, stats, exc)

        fallback = set(fb_encode)
        for docs, enc, _, _, res_np in resolved_groups:
            fallback.update(int(docs[i]) for i in enc.fallback_docs)
            # only the REAL rows: padding rows clamp-gather a neighbor's aux
            # and may carry its overflow flag
            fallback.update(
                int(docs[int(i)])
                for i in np.nonzero(res_np.overflow[: len(docs)])[0]
            )

        oracle_docs: Dict[int, Doc] = {}

        def oracle_doc_for(d: int) -> Doc:
            if d not in oracle_docs:
                oracle_docs[d] = _oracle_doc(workloads[d])
            return oracle_docs[d]

        cursor_positions: Optional[List[List[int]]] = None
        if cursors is not None:
            from ..ops.resolve import (
                oracle_cursor_positions,
                pack_cursor_rows,
                resolve_cursors_jit,
            )

            cursor_positions = [[] for _ in range(d_total)]
            for docs, enc, state_g, res_dev, _ in resolved_groups:
                local_map = {
                    i: list(cursors[int(d)])
                    for i, d in enumerate(docs)
                    if int(d) not in fallback
                }
                if not any(local_map.values()):
                    continue
                cursor_elem = pack_cursor_rows(
                    local_map, int(state_g.elem_id.shape[0]),
                    lambda i: enc.actor_tables[i],
                )
                positions = np.asarray(
                    resolve_cursors_jit(state_g, res_dev.visible, cursor_elem)
                )
                for i, d in enumerate(docs):
                    if int(d) not in fallback:
                        cursor_positions[int(d)] = [
                            int(p) for p in positions[i, : len(cursors[int(d)])]
                        ]
            for d in sorted(fallback):
                cursor_positions[d] = oracle_cursor_positions(
                    oracle_doc_for(d), cursors[d]
                )

        with self.tracer.span("batch.decode") as sp:
            spans: List[Optional[List[FormatSpan]]] = [None] * d_total
            roots: List[Optional[dict]] = [None] * d_total
            device_ops = 0
            fallback_ops = 0
            for docs, enc, state_g, _, res_np in resolved_groups:
                mask = np.zeros(res_np.visible.shape[0], bool)
                mask[: len(docs)] = [int(d) not in fallback for d in docs]
                block_spans = decode_block_spans(
                    res_np,
                    lambda i: enc.attr_tables[i],
                    lambda i: enc.attr_tables[i],
                    doc_mask=mask,
                )
                regs = SimpleNamespace(
                    r_obj=np.asarray(state_g.r_obj),
                    r_key=np.asarray(state_g.r_key),
                    r_op=np.asarray(state_g.r_op),
                    r_kind=np.asarray(state_g.r_kind),
                    r_val=np.asarray(state_g.r_val),
                    num_regs=np.asarray(state_g.num_regs),
                )
                for i, d in enumerate(docs):
                    d = int(d)
                    if d in fallback:
                        doc = oracle_doc_for(d)
                        spans[d] = doc.get_text_with_formatting(["text"])
                        roots[d] = doc.root
                        fallback_ops += int(enc.num_ops[i])
                    else:
                        spans[d] = block_spans[i]
                        roots[d] = decode_doc_root(
                            regs, res_np, i, enc.map_tables[i]
                        )
                        device_ops += int(enc.num_ops[i])
        stats.decode_seconds = sp.duration

        stats.device_ops = device_ops
        stats.fallback_ops = fallback_ops
        stats.fallback_docs = len(fallback)
        stats.device_docs = d_total - len(fallback)
        stats.padding_efficiency = (
            real_ops / stream_capacity if stream_capacity else 0.0
        )
        pool = store.pool_stats()
        stats.extras["layout_paged"] = 1.0
        stats.extras["page_pool_utilization"] = pool["pool_utilization"]
        stats.extras["page_internal_frag_ratio"] = pool["internal_frag_ratio"]
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(pool)
            GLOBAL_DEVPROF.sample_memory()
        GLOBAL_COUNTERS.add("merge.calls")
        GLOBAL_COUNTERS.add("merge.paged_calls")
        GLOBAL_COUNTERS.add("merge.device_ops", device_ops)
        GLOBAL_COUNTERS.add("merge.fallback_docs", len(fallback))
        return MergeReport(
            spans=spans,
            fallback_docs=sorted(fallback),
            device_ops=device_ops,
            stats=stats,
            cursor_positions=cursor_positions,
            roots=roots,
        )

    # -- ragged layout (ops/ragged.py over store/) ----------------------------

    def _merge_ragged(
        self,
        workloads: Sequence[Workload],
        cursors: Optional[Sequence[Sequence[dict]]],
    ) -> MergeReport:
        """merge() under ``layout="ragged"``: the whole batch is ONE group.
        Streams pad once to the batch's own true maxima, the page pool is
        pre-sized to the batch's true page demand, and a single
        ``ops/ragged.apply_batch_ragged`` dispatch walks every doc's true
        op count against its true pages — no power-of-two buckets anywhere,
        so the whole merge compiles exactly one apply executable regardless
        of the doc-size mix.  The padded path stays the byte-equality
        oracle, exactly as for "paged"."""
        import jax.numpy as jnp

        from ..ops.encode import _EMPTY_STREAMS, encode_doc_streams, pad_doc_streams
        from ..ops.ragged import apply_batch_ragged_jit, plan_arrays
        from ..store.paged import PagedDocStore, group_stream_arrays
        from ..store.ragged import ragged_plan

        stats = MergeStats(docs=len(workloads))
        d_total = len(workloads)
        with self.tracer.span("batch.encode") as sp:
            per_doc, fb_encode, actor_tables, attr_tables, map_tables = (
                encode_doc_streams(workloads)
            )
            fb_set = set(fb_encode)
            # per-doc capacity fallback thresholds: identical to the paged
            # path so the same docs fall back under every layout
            for d in range(d_total):
                s = per_doc[d]
                over = len(s.marks) > self.mark_capacity
                if self.op_capacity is not None:
                    over = over or len(s.ins) > self.op_capacity \
                        or len(s.dels) > self.op_capacity
                if over:
                    fb_set.add(d)
            enc = pad_doc_streams(
                [_EMPTY_STREAMS if d in fb_set else per_doc[d]
                 for d in range(d_total)],
                sorted(fb_set),
                actor_tables,
                attr_tables,
                map_tables=map_tables,
            )
        stats.encode_seconds = sp.duration

        try:
            with self.tracer.span("batch.apply") as sp:
                ins_counts = (np.asarray(enc.ins_op) != 0).sum(axis=1)
                del_counts = (np.asarray(enc.del_target) != 0).sum(axis=1)
                max_pages = max(1, self.slot_capacity // self.page_size)
                page_need = np.minimum(
                    -(-np.maximum(ins_counts, 1) // self.page_size), max_pages
                )
                store = PagedDocStore(
                    d_total,
                    slot_capacity=self.slot_capacity,
                    mark_capacity=self.mark_capacity,
                    tomb_capacity=enc.del_target.shape[1],
                    map_capacity=self.map_capacity,
                    page_size=self.page_size,
                    # page 0 is the null page; true demand, no bucket round
                    initial_pages=1 + int(page_need.sum()),
                )
                self.last_store = store
                rows = np.arange(d_total, dtype=np.int64)
                store.ensure_rows(rows, ins_counts)
                plan = ragged_plan(store)
                row_idx, owner, pos_base, prev_page, page_count, page_table = (
                    plan_arrays(plan)
                )
                store.pool_elem, store.pool_char, store.aux = (
                    apply_batch_ragged_jit(
                        store.pool_elem, store.pool_char, store.aux,
                        row_idx, owner, pos_base, prev_page, page_count,
                        page_table,
                        group_stream_arrays(enc, None, d_total),
                        jnp.asarray(ins_counts, jnp.int32),
                        jnp.asarray(del_counts, jnp.int32),
                    )
                )
                real_ops = int(enc.num_ops.sum())
                widths = (
                    enc.ins_op.shape[1], enc.del_target.shape[1],
                    next(iter(enc.marks.values())).shape[1],
                    next(iter(enc.map_ops.values())).shape[1],
                )
                if GLOBAL_DEVPROF.enabled:
                    # ragged pays real ops only: capacity IS the real work
                    GLOBAL_DEVPROF.observe_round(
                        occupancy_key(d_total, *widths),
                        real_ops, max(real_ops, 1),
                        origin="batch.merge.ragged",
                    )
                    GLOBAL_DEVPROF.observe_ragged(
                        docs_walked=plan.docs_walked,
                        pages_walked=plan.pages_walked,
                        real_ops=real_ops,
                    )
                # host sync: time apply honestly (mirror of _merge)
                np.asarray(store.aux_field("num_slots"))
            stats.apply_seconds = sp.duration

            with self.tracer.span("batch.resolve") as sp:
                # one materialize at the batch's true max page count — the
                # only place the ragged merge builds a dense block, and it
                # is sized by the data, not a bucket
                g_max = max(1, int(np.max(np.asarray(plan.page_count))))
                state = store.materialize_rows(rows, g_max)
                resolved_dev = self._resolve(state, self.comment_capacity)
                resolved = type(resolved_dev)(
                    *(np.asarray(x) for x in resolved_dev)
                )
            stats.resolve_seconds = sp.duration
        except Exception as exc:  # graftlint: boundary(guarded merge: ANY device-path failure degrades to the scalar oracle; re-raised when unguarded)
            if not self.guard:
                raise
            return self._degraded_merge(workloads, cursors, stats, exc)

        overflow = np.asarray(resolved.overflow)
        fallback = fb_set | set(enc.fallback_docs) | {
            int(d) for d in np.nonzero(overflow)[0] if d < d_total
        }

        oracle_docs: Dict[int, Doc] = {}

        def oracle_doc_for(d: int) -> Doc:
            if d not in oracle_docs:
                oracle_docs[d] = _oracle_doc(workloads[d])
            return oracle_docs[d]

        # row i IS doc i (one group, no bucket permutation), so the padded
        # path's batch cursor resolver applies verbatim
        cursor_positions: Optional[List[List[int]]] = None
        if cursors is not None:
            cursor_positions = self._resolve_cursor_batch(
                state, resolved_dev.visible, enc, cursors, fallback,
                oracle_doc_for,
            )

        with self.tracer.span("batch.decode") as sp:
            from types import SimpleNamespace

            from ..ops.decode import decode_doc_root

            device_mask = np.zeros(resolved.visible.shape[0], bool)
            for d in range(d_total):
                device_mask[d] = d not in fallback
            block_spans = decode_block_spans(
                resolved,
                lambda d: enc.attr_tables[d],
                lambda d: enc.attr_tables[d],
                doc_mask=device_mask,
            )
            regs = SimpleNamespace(
                r_obj=np.asarray(state.r_obj), r_key=np.asarray(state.r_key),
                r_op=np.asarray(state.r_op), r_kind=np.asarray(state.r_kind),
                r_val=np.asarray(state.r_val),
                num_regs=np.asarray(state.num_regs),
            )
            spans: List[List[FormatSpan]] = []
            roots: List[dict] = []
            device_ops = 0
            fallback_ops = 0
            for d, workload in enumerate(workloads):
                if d in fallback:
                    doc = oracle_doc_for(d)
                    spans.append(doc.get_text_with_formatting(["text"]))
                    roots.append(doc.root)
                    fallback_ops += int(enc.num_ops[d])
                else:
                    spans.append(block_spans[d])
                    roots.append(
                        decode_doc_root(regs, resolved, d, enc.map_tables[d])
                    )
                    device_ops += int(enc.num_ops[d])
        stats.decode_seconds = sp.duration

        stats.device_ops = device_ops
        stats.fallback_ops = fallback_ops
        stats.fallback_docs = len(fallback)
        stats.device_docs = d_total - len(fallback)
        # no pow-2 row bucket, no padded stream slots dispatched: the apply
        # walks true counts, so the occupancy ratio is 1.0 by construction
        stats.padding_efficiency = 1.0 if real_ops else 0.0
        pool = store.pool_stats()
        stats.extras["layout_ragged"] = 1.0
        stats.extras["page_pool_utilization"] = pool["pool_utilization"]
        stats.extras["page_internal_frag_ratio"] = pool["internal_frag_ratio"]
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(pool)
            GLOBAL_DEVPROF.sample_memory()
        GLOBAL_COUNTERS.add("merge.calls")
        GLOBAL_COUNTERS.add("merge.ragged_calls")
        GLOBAL_COUNTERS.add("merge.device_ops", device_ops)
        GLOBAL_COUNTERS.add("merge.fallback_docs", len(fallback))
        return MergeReport(
            spans=spans,
            fallback_docs=sorted(fallback),
            device_ops=device_ops,
            stats=stats,
            cursor_positions=cursor_positions,
            roots=roots,
        )

    def _degraded_merge(
        self, workloads, cursors, stats: MergeStats, exc: Exception
    ) -> MergeReport:
        """Guarded-merge degradation: the whole batch replays through the
        scalar oracle (byte-identical spans/roots/cursors, no device).  The
        failure is preserved as evidence in counters and ``stats.extras``."""
        from ..ops.resolve import oracle_cursor_positions

        GLOBAL_COUNTERS.add("merge.guarded_fallbacks")
        spans: List[List[FormatSpan]] = []
        roots: List[dict] = []
        positions: Optional[List[List[int]]] = [] if cursors is not None else None
        fallback_ops = 0
        with self.tracer.span("batch.degraded-replay", docs=len(workloads)) as sp:
            for d, workload in enumerate(workloads):
                doc = _oracle_doc(workload)
                spans.append(doc.get_text_with_formatting(["text"]))
                roots.append(doc.root)
                fallback_ops += sum(
                    len(ch.ops) for log in workload.values() for ch in log
                )
                if positions is not None:
                    positions.append(oracle_cursor_positions(doc, cursors[d]))
        stats.decode_seconds = sp.duration
        stats.fallback_docs = len(workloads)
        stats.device_docs = 0
        stats.fallback_ops = fallback_ops
        stats.extras["guarded_fallback"] = 1.0
        stats.extras["guarded_error"] = repr(exc)
        return MergeReport(
            spans=spans,
            fallback_docs=list(range(len(workloads))),
            device_ops=0,
            stats=stats,
            cursor_positions=positions,
            roots=roots,
        )

    def _resolve_cursor_batch(
        self, state, visible_dev, encoded, cursors, fallback, oracle_doc_for
    ) -> List[List[int]]:
        """Pack per-doc cursor element ids with each doc's actor table and
        resolve them on device in one batched call; fallback docs replay
        through the oracle (shared helpers in ops/resolve.py)."""
        from ..ops.resolve import (
            oracle_cursor_positions,
            pack_cursor_rows,
            resolve_cursors_jit,
        )

        num_docs = state.elem_id.shape[0]
        cursor_map = {
            d: doc_cursors
            for d, doc_cursors in enumerate(cursors)
            if d not in fallback
        }
        cursor_elem = pack_cursor_rows(
            cursor_map, num_docs, lambda d: encoded.actor_tables[d]
        )
        positions = np.asarray(
            resolve_cursors_jit(state, visible_dev, cursor_elem)
        )
        out: List[List[int]] = []
        for d, doc_cursors in enumerate(cursors):
            if d in fallback:
                out.append(oracle_cursor_positions(oracle_doc_for(d), doc_cursors))
            else:
                out.append([int(p) for p in positions[d, : len(doc_cursors)]])
        return out


def _oracle_doc(workload: Workload) -> Doc:
    doc = Doc("batch-fallback")
    for change in causal_sort([ch for log in workload.values() for ch in log]):
        doc.apply_change(change)
    return doc


def _oracle_spans(workload: Workload) -> List[FormatSpan]:
    return _oracle_doc(workload).get_text_with_formatting(["text"])


def oracle_merge(workloads: Sequence[Workload]) -> List[List[FormatSpan]]:
    """Scalar reference path for the same inputs (differential-test anchor)."""
    return [_oracle_spans(w) for w in workloads]
