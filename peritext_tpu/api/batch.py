"""DocBatch: the batched TPU merge backend.

The user-facing entry for the framework's north-star workload: given change
logs for D collaborative documents (each a dict actor -> [Change], exactly
what the replication layer accumulates), converge all of them at once on
device and return each document's formatted spans.

Pipeline: host causal sort + interning + stream splitting (ops/encode.py) ->
device batched apply (ops/kernel.py) -> device span resolution
(ops/resolve.py) -> host decode (ops/decode.py).  Documents the device path
cannot express (non-text objects, too many actors) or that overflow their
static capacities fall back to the scalar oracle (core/doc.py) transparently;
``MergeReport.fallback_docs`` says which.

Semantically equivalent to constructing a fresh ``core.Doc`` per workload and
replaying all changes — the differential tests assert exactly that equality.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.doc import Doc
from ..core.types import Change, FormatSpan
from ..obs import (
    GLOBAL_COUNTERS,
    GLOBAL_DEVPROF,
    GLOBAL_HISTOGRAMS,
    GLOBAL_TRACER,
    MergeStats,
    occupancy_key,
)
from ..ops.decode import decode_block_spans
from ..ops.encode import EncodedBatch, encode_workloads
from ..ops.kernel import apply_batch, apply_batch_jit, encoded_arrays_of
from ..ops.packed import PackedDocs, empty_docs
from ..ops.resolve import resolve, resolve_jit
from ..parallel.causal import causal_sort

Workload = Dict[str, List[Change]]


@dataclass
class MergeReport:
    """Outcome of a batched merge."""

    spans: List[List[FormatSpan]]
    #: doc indices resolved by the scalar oracle instead of the device
    fallback_docs: List[int] = field(default_factory=list)
    #: ops applied on device (excludes fallback docs)
    device_ops: int = 0
    #: per-merge observability (stage timings, padding efficiency)
    stats: MergeStats = field(default_factory=MergeStats)
    #: resolved cursor indices (aligned with merge()'s ``cursors`` argument);
    #: -1 = cursor's element does not exist in the converged document
    cursor_positions: Optional[List[List[int]]] = None
    #: per-doc materialized root map (nested maps + text list), equal to the
    #: scalar oracle's ``Doc.root`` — device docs decode their LWW register
    #: table (ops/decode.decode_doc_root), fallback docs replay
    roots: Optional[List[dict]] = None


class DocBatch:
    """Batched document merge engine.

    Capacities are static (XLA compiles one program per shape bucket):
    ``slot_capacity`` bounds elements-including-tombstones per doc,
    ``mark_capacity`` bounds mark ops per doc, ``comment_capacity`` bounds
    distinct interned attrs per doc, ``op_capacity`` bounds the insert and
    delete streams per merge call (None = sized to the batch).
    """

    def __init__(
        self,
        slot_capacity: int = 256,
        mark_capacity: int = 64,
        comment_capacity: int = 32,
        op_capacity: Optional[int] = None,
        map_capacity: int = 32,
        jit: bool = True,
        mesh=None,
        guard: bool = False,
        tracer=None,
    ) -> None:
        #: pipeline-span producer (obs/spans.py): merge() opens a
        #: ``batch.merge`` span with encode/apply/resolve/decode children,
        #: whose durations also feed MergeStats — one clock, two surfaces
        self.tracer = tracer if tracer is not None else GLOBAL_TRACER
        self.slot_capacity = slot_capacity
        self.mark_capacity = mark_capacity
        self.comment_capacity = comment_capacity
        self.op_capacity = op_capacity
        self.map_capacity = map_capacity
        #: fault-domain guard: a device-stage failure (XLA compile/runtime
        #: error, device OOM) degrades the whole merge to the scalar oracle
        #: — slower but byte-identical — instead of raising.  Off by default
        #: so development surfaces device bugs loudly; the supervisor layer
        #: turns it on for production serving.
        self.guard = guard
        #: optional jax.sharding.Mesh; when set, the doc axis of every tensor
        #: is sharded across it (pure data parallelism; XLA adds collectives
        #: only for cross-doc reductions like the convergence digest).
        self.mesh = mesh
        # Reuse the module-level jitted wrappers: JAX's compilation cache is
        # keyed per-wrapper, so per-instance jax.jit would recompile the same
        # kernel for every DocBatch.
        self._apply = apply_batch_jit if jit else apply_batch
        self._resolve = resolve_jit if jit else resolve

    # -- device pipeline ---------------------------------------------------

    def encode(self, workloads: Sequence[Workload]) -> EncodedBatch:
        return encode_workloads(
            list(workloads),
            insert_capacity=self.op_capacity,
            delete_capacity=self.op_capacity,
            mark_capacity=self.mark_capacity,
        )

    def apply_encoded(self, encoded: EncodedBatch) -> PackedDocs:
        """Run the batched two-phase apply on an encoded batch."""
        arrays = encoded_arrays_of(encoded)
        num_docs = encoded.num_docs
        if self.mesh is not None:
            from ..parallel.mesh import pad_doc_axis, shard_docs
            import jax

            arrays = jax.tree_util.tree_map(
                lambda x: pad_doc_axis(np.asarray(x), self.mesh.size), arrays
            )
            arrays = shard_docs(arrays, self.mesh)
            num_docs = arrays[0].shape[0]
        state = empty_docs(
            num_docs,
            self.slot_capacity,
            self.mark_capacity,
            tomb_capacity=arrays[3].shape[1],  # delete-stream width
            map_capacity=self.map_capacity,
        )
        if self.mesh is not None:
            from ..parallel.mesh import shard_docs

            state = shard_docs(state, self.mesh)
        return self._apply(state, arrays)

    def merge(
        self,
        workloads: Sequence[Workload],
        cursors: Optional[Sequence[Sequence[dict]]] = None,
    ) -> MergeReport:
        """Converge every workload; returns per-doc formatted spans.

        ``cursors`` optionally gives, per document, stable cursors
        (``{"objectId", "elemId"}``, the reference's ``Cursor`` shape,
        src/micromerge.ts:859-870) to resolve against the converged state;
        resolved visible indices land in ``MergeReport.cursor_positions``
        (-1 when the cursor's element is absent).  Device docs resolve on
        device (ops/resolve.resolve_cursors); fallback docs via the oracle.
        """
        with self.tracer.span("batch.merge", docs=len(workloads)) as sp:
            report = self._merge(workloads, cursors)
        GLOBAL_HISTOGRAMS.observe("merge.seconds", sp.duration)
        return report

    def _merge(
        self,
        workloads: Sequence[Workload],
        cursors: Optional[Sequence[Sequence[dict]]],
    ) -> MergeReport:
        """merge() behind its pipeline span: each stage runs under a child
        span whose duration doubles as the MergeStats stage wall-clock."""
        stats = MergeStats(docs=len(workloads))
        with self.tracer.span("batch.encode") as sp:
            encoded = self.encode(workloads)
        stats.encode_seconds = sp.duration

        try:
            with self.tracer.span("batch.apply") as sp:
                state = self.apply_encoded(encoded)
                np.asarray(state.num_slots)  # host sync: time apply honestly
            stats.apply_seconds = sp.duration

            with self.tracer.span("batch.resolve") as sp:
                resolved_dev = self._resolve(state, self.comment_capacity)
                # One whole-array transfer per field, up front: decoding per
                # doc on the raw (possibly mesh-sharded) arrays would do 5
                # device gathers per document.
                resolved = type(resolved_dev)(
                    *(np.asarray(x) for x in resolved_dev)
                )
            stats.resolve_seconds = sp.duration
        except Exception as exc:  # graftlint: boundary(guarded merge: ANY device-path failure degrades to the scalar oracle; re-raised when unguarded)
            if not self.guard:
                raise
            return self._degraded_merge(workloads, cursors, stats, exc)

        overflow = np.asarray(resolved.overflow)
        fallback = set(encoded.fallback_docs) | {
            int(d) for d in np.nonzero(overflow)[0] if d < len(workloads)
        }

        # Fallback docs may be replayed for both cursors and spans; build each
        # oracle doc at most once per merge.
        oracle_docs: Dict[int, Doc] = {}

        def oracle_doc_for(d: int) -> Doc:
            if d not in oracle_docs:
                oracle_docs[d] = _oracle_doc(workloads[d])
            return oracle_docs[d]

        cursor_positions: Optional[List[List[int]]] = None
        if cursors is not None:
            cursor_positions = self._resolve_cursor_batch(
                state, resolved_dev.visible, encoded, cursors, fallback, oracle_doc_for
            )

        with self.tracer.span("batch.decode") as sp:
            from ..ops.decode import decode_doc_root
            from types import SimpleNamespace

            # register table transfer (small: 5 x (D, R) int32)
            regs = SimpleNamespace(
                r_obj=np.asarray(state.r_obj), r_key=np.asarray(state.r_key),
                r_op=np.asarray(state.r_op), r_kind=np.asarray(state.r_kind),
                r_val=np.asarray(state.r_val), num_regs=np.asarray(state.num_regs),
            )
            # one vectorized span decode for the whole batch (Python touches
            # only mark-run segments); fallback docs replay through the oracle
            device_mask = np.zeros(resolved.visible.shape[0], bool)
            for d in range(len(workloads)):
                device_mask[d] = d not in fallback
            block_spans = decode_block_spans(
                resolved,
                lambda d: encoded.attr_tables[d],
                lambda d: encoded.attr_tables[d],
                doc_mask=device_mask,
            )
            spans: List[List[FormatSpan]] = []
            roots: List[dict] = []
            device_ops = 0
            fallback_ops = 0
            for d, workload in enumerate(workloads):
                if d in fallback:
                    doc = oracle_doc_for(d)
                    spans.append(doc.get_text_with_formatting(["text"]))
                    roots.append(doc.root)
                    fallback_ops += int(encoded.num_ops[d])
                else:
                    spans.append(block_spans[d])
                    roots.append(
                        decode_doc_root(regs, resolved, d, encoded.map_tables[d])
                    )
                    device_ops += int(encoded.num_ops[d])
        stats.decode_seconds = sp.duration

        stream_capacity = encoded.num_docs * (
            encoded.ins_op.shape[1]
            + encoded.del_target.shape[1]
            + next(iter(encoded.marks.values())).shape[1]
            + next(iter(encoded.map_ops.values())).shape[1]
        )
        stats.device_ops = device_ops
        stats.fallback_ops = fallback_ops
        stats.fallback_docs = len(fallback)
        stats.device_docs = len(workloads) - len(fallback)
        stats.padding_efficiency = (
            float(encoded.num_ops.sum()) / stream_capacity if stream_capacity else 0.0
        )
        if GLOBAL_DEVPROF.enabled:
            # one-shot batch merges land in the same bucket-occupancy table
            # as streaming rounds, keyed by their padded stream widths
            GLOBAL_DEVPROF.observe_round(
                occupancy_key(
                    encoded.num_docs,
                    encoded.ins_op.shape[1],
                    encoded.del_target.shape[1],
                    next(iter(encoded.marks.values())).shape[1],
                    next(iter(encoded.map_ops.values())).shape[1],
                ),
                int(encoded.num_ops.sum()), stream_capacity,
                origin="batch.merge",
            )
            GLOBAL_DEVPROF.sample_memory()
        GLOBAL_COUNTERS.add("merge.calls")
        GLOBAL_COUNTERS.add("merge.device_ops", device_ops)
        GLOBAL_COUNTERS.add("merge.fallback_docs", len(fallback))
        return MergeReport(
            spans=spans,
            fallback_docs=sorted(fallback),
            device_ops=device_ops,
            stats=stats,
            cursor_positions=cursor_positions,
            roots=roots,
        )

    def _degraded_merge(
        self, workloads, cursors, stats: MergeStats, exc: Exception
    ) -> MergeReport:
        """Guarded-merge degradation: the whole batch replays through the
        scalar oracle (byte-identical spans/roots/cursors, no device).  The
        failure is preserved as evidence in counters and ``stats.extras``."""
        from ..ops.resolve import oracle_cursor_positions

        GLOBAL_COUNTERS.add("merge.guarded_fallbacks")
        spans: List[List[FormatSpan]] = []
        roots: List[dict] = []
        positions: Optional[List[List[int]]] = [] if cursors is not None else None
        fallback_ops = 0
        with self.tracer.span("batch.degraded-replay", docs=len(workloads)) as sp:
            for d, workload in enumerate(workloads):
                doc = _oracle_doc(workload)
                spans.append(doc.get_text_with_formatting(["text"]))
                roots.append(doc.root)
                fallback_ops += sum(
                    len(ch.ops) for log in workload.values() for ch in log
                )
                if positions is not None:
                    positions.append(oracle_cursor_positions(doc, cursors[d]))
        stats.decode_seconds = sp.duration
        stats.fallback_docs = len(workloads)
        stats.device_docs = 0
        stats.fallback_ops = fallback_ops
        stats.extras["guarded_fallback"] = 1.0
        stats.extras["guarded_error"] = repr(exc)
        return MergeReport(
            spans=spans,
            fallback_docs=list(range(len(workloads))),
            device_ops=0,
            stats=stats,
            cursor_positions=positions,
            roots=roots,
        )

    def _resolve_cursor_batch(
        self, state, visible_dev, encoded, cursors, fallback, oracle_doc_for
    ) -> List[List[int]]:
        """Pack per-doc cursor element ids with each doc's actor table and
        resolve them on device in one batched call; fallback docs replay
        through the oracle (shared helpers in ops/resolve.py)."""
        from ..ops.resolve import (
            oracle_cursor_positions,
            pack_cursor_rows,
            resolve_cursors_jit,
        )

        num_docs = state.elem_id.shape[0]
        cursor_map = {
            d: doc_cursors
            for d, doc_cursors in enumerate(cursors)
            if d not in fallback
        }
        cursor_elem = pack_cursor_rows(
            cursor_map, num_docs, lambda d: encoded.actor_tables[d]
        )
        positions = np.asarray(
            resolve_cursors_jit(state, visible_dev, cursor_elem)
        )
        out: List[List[int]] = []
        for d, doc_cursors in enumerate(cursors):
            if d in fallback:
                out.append(oracle_cursor_positions(oracle_doc_for(d), doc_cursors))
            else:
                out.append([int(p) for p in positions[d, : len(doc_cursors)]])
        return out


def _oracle_doc(workload: Workload) -> Doc:
    doc = Doc("batch-fallback")
    for change in causal_sort([ch for log in workload.values() for ch in log]):
        doc.apply_change(change)
    return doc


def _oracle_spans(workload: Workload) -> List[FormatSpan]:
    return _oracle_doc(workload).get_text_with_formatting(["text"])


def oracle_merge(workloads: Sequence[Workload]) -> List[List[FormatSpan]]:
    """Scalar reference path for the same inputs (differential-test anchor)."""
    return [_oracle_spans(w) for w in workloads]
