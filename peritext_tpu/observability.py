"""Metrics, structured event logging, and profiling hooks.

The reference's observability is ``console.log`` plus demo DOM panels (SURVEY
§5.5); this module supplies the framework-grade replacements it calls for:

* :class:`Counters` — process-local counters/timers for the north-star
  metrics (ops applied per second per chip, convergence wall-clock, padding
  efficiency of the static-shape batches).
* :class:`EventLog` — structured, append-only JSON-lines event stream
  (replaces the reference's DOM change log, ``outputDebugForChange``
  src/bridge.ts:235-242); works as an ``Editor.on_event`` sink and a general
  framework event bus.
* :func:`profile_trace` — context manager around ``jax.profiler`` traces for
  TensorBoard/Perfetto viewing; no-ops cleanly when profiling is unavailable
  so library code can call it unconditionally.
* :class:`MergeStats` — per-merge report: device vs fallback op counts,
  stage wall-clocks, and padding efficiency (the fraction of padded device
  work that was real), attached to ``DocBatch.merge`` results.
"""

from __future__ import annotations

import contextlib
import json
import logging
import re
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, IO, Iterator, Optional


class Counters:
    """Thread-safe named counters and accumulated timings."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = defaultdict(float)

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counts[name] += value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counts.get(name, 0.0)

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - start)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


#: Default process-wide counters.
GLOBAL_COUNTERS = Counters()


#: counter namespaces that make up the fault-domain health surface
_HEALTH_PREFIXES = ("streaming.", "transport.", "supervisor.", "merge.", "jit.")


def health_snapshot(
    counters: Optional[Counters] = None, session=None, sentinel=None
) -> Dict[str, Any]:
    """One structured dict for a fleet health endpoint: every fault-domain
    counter (quarantines, corrupt frames, transport retries / behind peers,
    supervisor rollbacks, guarded-merge fallbacks, per-jit-site compile
    counts), plus — when a streaming session or its
    :class:`~.parallel.supervisor.GuardedSession` is given — that session's
    own ``health()`` (quarantine registry with typed reasons,
    fallback/pending counts, rollback evidence).  With a
    :class:`RecompileSentinel` attached, its per-site compile counts appear
    under ``recompiles`` (the counter form lands under ``counters`` as
    ``jit.compiles.*`` either way)."""
    counters = counters or GLOBAL_COUNTERS
    out: Dict[str, Any] = {
        "counters": {
            k: v
            for k, v in sorted(counters.snapshot().items())
            if k.startswith(_HEALTH_PREFIXES)
        },
    }
    if session is not None:
        out["session"] = session.health()
    if sentinel is not None:
        out["recompiles"] = {
            "sites": dict(sorted(sentinel.counts.items())),
            "total": sentinel.total,
        }
    return out


#: jax's log_compiles emission: "Compiling <site> with global shapes and
#: types ..." (pxla) / "Compiling <site> for ..." (older dispatch paths)
_COMPILE_MSG_RE = re.compile(r"^Compiling (\S+)")


class RecompileSentinel(logging.Handler):
    """Runtime guard for the compile-shape discipline (DESIGN.md "compile-
    shape discipline", graftlint PTL004): counts XLA compilations **per jit
    site** so steady-state streaming rounds can assert *zero* recompiles.

    Backed by ``jax_log_compiles``: while active, jax logs one
    ``Compiling <site> ...`` record per executable built, and this handler
    (attached to the ``"jax"`` logger) tallies it — no private APIs, no
    tracing overhead beyond the log call.  Counts land three ways:

    * :attr:`counts` — ``{site: compiles}`` on the sentinel itself;
    * ``jit.compiles.<site>`` / ``jit.compiles_total`` on the target
      :class:`Counters` (default :data:`GLOBAL_COUNTERS`), which
      :func:`health_snapshot` exports;
    * ``health_snapshot(sentinel=s)`` embeds the per-site dict directly.

    Use as a context manager; :meth:`mark` + :meth:`assert_steady_state`
    express the invariant tests care about::

        with RecompileSentinel() as s:
            warmup_rounds(session)
            s.mark()
            steady_rounds(session)
            s.assert_steady_state("steady-state streaming rounds")
    """

    def __init__(self, counters: Optional[Counters] = None, logger: str = "jax"):
        super().__init__(level=logging.DEBUG)
        self.counts: Dict[str, int] = {}
        self._marked: Dict[str, int] = {}
        self._counters = counters if counters is not None else GLOBAL_COUNTERS
        self._logger = logging.getLogger(logger)
        self._prev_log_compiles: Optional[bool] = None
        self._active = False

    # -- logging.Handler ------------------------------------------------------

    def emit(self, record: logging.LogRecord) -> None:
        try:
            message = record.getMessage()
        except Exception:  # graftlint: boundary(malformed foreign log records are ignored, never raised into the workload)
            return
        m = _COMPILE_MSG_RE.match(message)
        if m is None:
            return
        site = m.group(1)
        self.counts[site] = self.counts.get(site, 0) + 1
        self._counters.add(f"jit.compiles.{site}")
        self._counters.add("jit.compiles_total")

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "RecompileSentinel":
        if self._active:
            return self
        import jax

        self._prev_log_compiles = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        self._logger.addHandler(self)
        self._active = True
        return self

    def stop(self) -> None:
        if not self._active:
            return
        self._logger.removeHandler(self)
        try:
            import jax

            jax.config.update("jax_log_compiles", self._prev_log_compiles)
        except Exception:  # graftlint: boundary(best-effort config restore on teardown; the counts already collected stay valid)
            pass
        self._active = False

    def __enter__(self) -> "RecompileSentinel":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    # -- assertions -----------------------------------------------------------

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def mark(self) -> None:
        """Snapshot the current counts; :meth:`since_mark` and
        :meth:`assert_steady_state` measure growth from here."""
        self._marked = dict(self.counts)

    def since_mark(self) -> Dict[str, int]:
        """Per-site compiles since :meth:`mark` (empty dict = steady state)."""
        return {
            site: n - self._marked.get(site, 0)
            for site, n in sorted(self.counts.items())
            if n > self._marked.get(site, 0)
        }

    def assert_steady_state(self, what: str = "steady-state rounds") -> None:
        fresh = self.since_mark()
        if fresh:
            raise AssertionError(
                f"{what} triggered {sum(fresh.values())} recompile(s): {fresh} "
                "— a per-round shape escaped the padded-shape tables "
                "(see DESIGN.md compile-shape discipline / graftlint PTL004)"
            )


class EventLog:
    """Append-only structured event stream.

    Events are plain dicts with a ``kind``; every record gets a monotonic
    sequence number and a wall-clock timestamp.  Optionally tees each record
    to a JSON-lines file.  Usable directly as an ``Editor.on_event`` sink.
    """

    def __init__(self, path: Optional[str | Path] = None, capacity: Optional[int] = 10000):
        self._lock = threading.Lock()
        self._events: list = []
        self._seq = 0
        self.capacity = capacity
        self._file: Optional[IO[str]] = open(path, "a") if path is not None else None

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        record = {"seq": None, "ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            self._events.append(record)
            if self.capacity is not None and len(self._events) > self.capacity:
                self._events = self._events[-self.capacity :]
            if self._file is not None:
                self._file.write(json.dumps(record, default=str) + "\n")
                self._file.flush()
        return record

    # Editor.on_event sink (bridge.EditorEvent)
    def __call__(self, editor_event) -> None:
        self.emit(
            f"editor.{editor_event.kind}", actor=editor_event.actor, **editor_event.detail
        )

    def events(self, kind: Optional[str] = None) -> list:
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if kind is None or e["kind"] == kind] if kind else evs

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


@contextlib.contextmanager
def profile_trace(log_dir: str | Path, enabled: bool = True) -> Iterator[None]:
    """Capture a JAX profiler trace (viewable in TensorBoard / Perfetto) for
    the enclosed block.  Silently degrades to a no-op if the profiler is
    unavailable on the current platform."""
    if not enabled:
        yield
        return
    try:
        import jax

        jax.profiler.start_trace(str(log_dir))
        started = True
    except Exception:  # graftlint: boundary(profiler availability is platform-defined; tracing must never fail the traced workload)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # graftlint: boundary(stop mirrors start: a torn trace is dropped, never raised into the workload)
                pass


@dataclass
class MergeStats:
    """Per-merge observability (attached to ``api.batch.MergeReport``)."""

    docs: int = 0
    device_docs: int = 0
    fallback_docs: int = 0
    device_ops: int = 0
    fallback_ops: int = 0
    encode_seconds: float = 0.0
    apply_seconds: float = 0.0
    resolve_seconds: float = 0.0
    decode_seconds: float = 0.0
    #: real ops / padded op-stream capacity across the batch (0..1)
    padding_efficiency: float = 0.0
    extras: Dict[str, float] = field(default_factory=dict)

    @property
    def total_seconds(self) -> float:
        return (
            self.encode_seconds
            + self.apply_seconds
            + self.resolve_seconds
            + self.decode_seconds
        )

    @property
    def device_ops_per_sec(self) -> float:
        wall = self.apply_seconds
        return self.device_ops / wall if wall > 0 else 0.0

    def to_json(self) -> Dict[str, Any]:
        return {
            "docs": self.docs,
            "device_docs": self.device_docs,
            "fallback_docs": self.fallback_docs,
            "device_ops": self.device_ops,
            "fallback_ops": self.fallback_ops,
            "encode_seconds": round(self.encode_seconds, 6),
            "apply_seconds": round(self.apply_seconds, 6),
            "resolve_seconds": round(self.resolve_seconds, 6),
            "decode_seconds": round(self.decode_seconds, 6),
            "padding_efficiency": round(self.padding_efficiency, 4),
            "device_ops_per_sec": round(self.device_ops_per_sec, 1),
            **self.extras,
        }
