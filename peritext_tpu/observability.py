"""Back-compat shim: the observability layer grew into the
:mod:`peritext_tpu.obs` package (spans/tracing, histograms, flight
recorder, exporters — see its docstring).  Every historical name re-exports
from there, unchanged in identity (``GLOBAL_COUNTERS`` here IS
``peritext_tpu.obs.GLOBAL_COUNTERS``), so existing imports keep working.
New code should import from :mod:`peritext_tpu.obs` directly.
"""

from __future__ import annotations

from .obs import (  # noqa: F401
    ConvergenceMonitor,
    Counters,
    EventLog,
    FlightRecorder,
    GLOBAL_COUNTERS,
    GLOBAL_HISTOGRAMS,
    GLOBAL_TRACER,
    Histogram,
    HistogramRegistry,
    LATENCY_BUCKETS_S,
    MergeStats,
    MetricsServer,
    RecompileSentinel,
    SIZE_BUCKETS,
    Span,
    TraceContext,
    Tracer,
    health_snapshot,
    merge_traces,
    profile_trace,
    prometheus_text,
)
from .obs.metrics import _HEALTH_PREFIXES  # noqa: F401
from .obs.sentinel import _COMPILE_MSG_RE  # noqa: F401

__all__ = [
    "ConvergenceMonitor",
    "Counters",
    "EventLog",
    "FlightRecorder",
    "GLOBAL_COUNTERS",
    "GLOBAL_HISTOGRAMS",
    "GLOBAL_TRACER",
    "Histogram",
    "HistogramRegistry",
    "LATENCY_BUCKETS_S",
    "MergeStats",
    "MetricsServer",
    "RecompileSentinel",
    "SIZE_BUCKETS",
    "Span",
    "TraceContext",
    "Tracer",
    "health_snapshot",
    "merge_traces",
    "profile_trace",
    "prometheus_text",
]
