#!/usr/bin/env python
"""peritext-tpu benchmark: batched CRDT op application throughput.

Measures the north-star metric (BASELINE.md): CRDT ops applied/sec/chip for
converging a batch of concurrently-edited documents, vs the single-thread
scalar baseline.

Baseline caveat: BASELINE.json config 1 calls for the reference TypeScript
micromerge on one CPU core, but this image has no node runtime.  Two
stand-ins are measured every run: the C++ single-core scalar apply
(``native.pt_scalar_apply`` — a HARDER bar than interpreted TS; this is
what ``vs_baseline`` divides by) and the framework's own pure-Python scalar
oracle (continuity with round-1 records, reported as
``python_oracle_ops_per_sec``).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N, ...extras}

Evidence-capture discipline (round 3): the default entry point is an
ORCHESTRATOR that never hangs and always prints that JSON line.  It probes
the TPU backend in a subprocess under a bounded timeout (the axon tunnel
has been observed to hang ``jax.devices()`` indefinitely when down), retries
a couple of times, and on persistent unavailability reruns the measurement
on CPU — exit 0, with ``"tpu_unavailable": true`` and the captured error
tail merged into the JSON.  The actual measurement runs in a worker
subprocess (hidden ``--_worker`` flag) that is itself under a timeout, so a
mid-benchmark wedge also converts into a structured record instead of a
lost round.  Set ``PT_BENCH_SIMULATE_TPU=hang|fail`` to exercise the
dead-tunnel paths without a tunnel (used by tests/test_bench_harness.py).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

# Bounded-timeout policy for the orchestrator (seconds; env-overridable so
# the driver or tests can tighten them).
# Probe budget: the probe is a TINY device round-trip (8 ints), so 120 s
# covers even the measured 60x shared-chip noise with two orders of margin;
# 3 attempts keep resilience against transient tunnel flaps while bounding a
# DEAD tunnel's total cost (~6 min probe + CPU-fallback worker) safely under
# the driver's capture window.
PROBE_TIMEOUT = float(os.environ.get("PT_BENCH_PROBE_TIMEOUT", "120"))
PROBE_ATTEMPTS = int(os.environ.get("PT_BENCH_PROBE_ATTEMPTS", "3"))
PROBE_BACKOFF = float(os.environ.get("PT_BENCH_PROBE_BACKOFF", "5"))
WORKER_TIMEOUT = float(os.environ.get("PT_BENCH_TIMEOUT", "2700"))
# Ladder mode (the no-args default): per-row worker timeout and a global
# deadline after which remaining rows are recorded as skipped — one slow or
# wedged row must never cost the round its entire evidence record.
ROW_TIMEOUT = float(os.environ.get("PT_BENCH_ROW_TIMEOUT", "900"))
LADDER_DEADLINE = float(os.environ.get("PT_BENCH_LADDER_DEADLINE", "3600"))
# The driver keeps only a short tail of stdout; round 4's single ~5 KB JSON
# line outgrew it and BENCH_r04.json recorded parsed=null (VERDICT r4 weak
# #1).  The ladder therefore prints a COMPACT summary as the LAST line —
# hard-budgeted below — and writes the full rows to a sidecar file.
FINAL_LINE_BUDGET = int(os.environ.get("PT_BENCH_FINAL_LINE_BUDGET", "1536"))
SIDECAR = os.environ.get(
    "PT_BENCH_SIDECAR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_self.json"),
)

# The probe child: initialize the default jax backend (axon plugin when the
# tunnel is up, else cpu) AND round-trip one tiny device computation —
# backend init succeeding while the first computation wedges was round 2's
# observed failure mode.  PT_BENCH_SIMULATE_TPU lets tests exercise the
# hang/fail paths deterministically on a CPU-only image.
_PROBE_CODE = r"""
import os, sys, time
sim = os.environ.get("PT_BENCH_SIMULATE_TPU", "")
if sim == "hang":
    time.sleep(100000)
if sim == "fail":
    sys.stderr.write("RuntimeError: simulated TPU backend failure (PT_BENCH_SIMULATE_TPU=fail)\n")
    sys.exit(1)
import jax
if sim == "cpu":  # simulate an image with no TPU plugin attached
    jax.config.update("jax_platforms", "cpu")
import numpy as np
dev = jax.devices()[0]
x = jax.device_put(np.arange(8, dtype=np.int32))
total = int(np.asarray(x + 1).sum())  # honest sync: small host transfer
assert total == 36, total
print("PROBE_OK", dev.platform)
"""


def _baseline_changes(num_ops: int = 4000, seed: int = 7):
    """Causally-ordered fuzz change log shared by both scalar baselines."""
    from peritext_tpu.parallel.causal import causal_sort
    from peritext_tpu.testing.fuzz import make_fuzz_state, fuzz_step

    state = make_fuzz_state(seed, num_replicas=3)
    while state.ops_generated < num_ops:
        fuzz_step(state, check=False)
    changes = causal_sort(
        [ch for actor in state.store.actors() for ch in state.store.log(actor)]
    )
    return changes, sum(len(ch.ops) for ch in changes)


def measure_scalar_baseline(num_ops: int = 4000, seed: int = 7) -> float:
    """Single-thread ops/sec: replay fuzz-generated change logs through the
    scalar oracle's apply_change path (pure Python)."""
    from peritext_tpu.core.doc import Doc

    changes, total_ops = _baseline_changes(num_ops, seed)
    doc = Doc("baseline")
    t0 = time.perf_counter()
    for ch in changes:
        doc.apply_change(ch)
    elapsed = time.perf_counter() - t0
    return total_ops / elapsed


def measure_native_baseline(num_docs: int = 16, ops_per_doc: int = 256, seed: int = 7):
    """Single-CORE ops/sec through the C++ scalar apply (pt_scalar_apply) —
    the defensible stand-in for the reference's single-thread TS baseline
    (no node runtime in this image; an optimized native single core is a
    strictly harder bar than interpreted TS, which pays for JS objects,
    per-mark gap-set maintenance and patch emission this baseline skips).
    Callers pass the device benchmark's ops_per_doc so per-op scan lengths
    match the workload being compared against.  Every doc's applied text is
    validated against the Python oracle before timing.  Returns None if the
    native core is unavailable."""
    from peritext_tpu import native
    from peritext_tpu.testing.baseline import (
        check_scalar_apply_matches_oracle,
        workload_op_matrices,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    if not native.available():
        return None
    workloads = generate_workload(seed, num_docs=num_docs, ops_per_doc=ops_per_doc)
    matrices, total_ops = workload_op_matrices(workloads)
    check_scalar_apply_matches_oracle(workloads, matrices)

    # a single sweep is fast; amortize wrapper overhead over repetitions
    reps = 20
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            for m in matrices:
                native.scalar_apply(m)
        dt = (time.perf_counter() - t0) / reps
        best = dt if best is None or dt < best else best
    return total_ops / best


def _baselines_for(ops_per_doc: int, seed: int):
    """(python_oracle, native_cpp) baselines — reused from the ladder's
    baselines row via PT_BENCH_BASELINES when the shapes match, else
    measured in-process (the scalar baselines cost ~30 s each, too much to
    re-pay in every ladder row)."""
    blob = os.environ.get("PT_BENCH_BASELINES")
    if blob:
        try:
            b = json.loads(blob)
        except json.JSONDecodeError:
            b = None
        if b and b.get("scalar_python_ops_per_sec"):
            python = b["scalar_python_ops_per_sec"]
            if b.get("native_ops_per_doc") == ops_per_doc and \
                    b.get("native_cpp_ops_per_sec"):
                return python, b["native_cpp_ops_per_sec"]
            return python, measure_native_baseline(ops_per_doc=ops_per_doc, seed=seed)
    return (
        measure_scalar_baseline(),
        measure_native_baseline(ops_per_doc=ops_per_doc, seed=seed),
    )


def run(args) -> dict:
    import jax

    if args.platform:
        # The axon plugin pins jax_platforms at config level, so a plain
        # JAX_PLATFORMS env var is not enough to redirect the bench.
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.ops.kernel import apply_batch_jit
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.ops.resolve import resolve_jit
    from peritext_tpu.testing.synth import synth_streams, synth_total_ops

    d, k, s, m = args.docs, args.ops_per_doc, args.slots, args.marks
    if args.layout == "ragged":
        # the ragged store pages the element planes: round the shared slot
        # capacity to a page multiple so both layouts overflow at the same
        # op (cap = page_count * P must be able to equal S exactly)
        from peritext_tpu.store import DEFAULT_PAGE_SIZE

        s = -(-s // DEFAULT_PAGE_SIZE) * DEFAULT_PAGE_SIZE
    # op mix matching the fuzz distribution: ~70% inserts, 15% deletes, 15% marks
    ki = int(k * 0.7)
    kd = int(k * 0.15)
    km = k - ki - kd

    gen_start = time.perf_counter()
    streams = synth_streams(
        d, inserts_per_doc=ki, deletes_per_doc=kd, marks_per_doc=km, seed=args.seed
    )
    total_ops = synth_total_ops(streams)
    gen_time = time.perf_counter() - gen_start

    state0 = empty_docs(d, s, max(m, km), tomb_capacity=max(kd, 8))
    ops_dev = jax.device_put(streams)

    # Docs start empty here, so the insert loop can be statically bounded to
    # the insert-stream width (pallas_insert loop_slots contract).
    def apply_jit(st, ops):
        return apply_batch_jit(st, ops, insert_loop_slots=ki)

    # NOTE: jax.block_until_ready does not actually block on the axon TPU
    # platform; force a small host transfer to synchronize honestly.
    def sync(r):
        return np.asarray(r.num_slots)

    compile_start = time.perf_counter()
    result = apply_jit(state0, ops_dev)
    sync(result)
    compile_time = time.perf_counter() - compile_start

    if args.layout == "ragged":
        # the batch_8k_ragged row (ISSUE 12): same streams, same protocol,
        # but the apply runs ragged over a page pool — the padded result
        # just computed is its byte-equality oracle
        return _batch_ragged_tail(
            args, ops_dev, state0, apply_jit, sync, result, total_ops,
            gen_time, compile_time, d=d, s=s, mark_cap=max(m, km),
            tomb_cap=max(kd, 8),
        )

    # single_call_seconds DEFINITION (stable across rounds; VERDICT r4 task
    # 7): wall time of ONE whole-batch apply dispatch through to a host
    # sync on a small output — i.e. per-op latency, = apply compute + the
    # platform's fixed dispatch+sync round trip.  Through the axon tunnel
    # that fixed term measured 0.08-0.11 s round 5 (scripts/
    # engine_profile.py --fine, dispatch+fetch of an 8-int program), and it
    # varies with tunnel load — so this field tracks LINK latency, while
    # apply_seconds (back-to-back enqueue, one sync) tracks the chip.  The
    # r2->r4 drift 0.032->0.149 s was the tunnel term, not a kernel
    # regression: apply_seconds held 0.032->0.037 across the same rounds.
    t0 = time.perf_counter()
    sync(apply_jit(state0, ops_dev))
    single_call = time.perf_counter() - t0

    # Steady-state throughput (the headline): enqueue iters applies
    # back-to-back — the device executes queued programs serially — and
    # sync once, amortizing dispatch latency exactly as a streaming
    # deployment does.
    from peritext_tpu.observability import profile_trace

    times = []
    with profile_trace(args.profile, enabled=args.profile is not None):
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(args.iters):
                result = apply_jit(state0, ops_dev)
            sync(result)
            times.append(time.perf_counter() - t0)
    best = min(times) / args.iters

    overflow = int(np.asarray(result.overflow).sum())
    device_ops_per_sec = total_ops / best

    # resolution (read path) timing, reported as extra context; sync on a
    # small field (visible is (D,S) and would measure the host transfer).
    resolved = resolve_jit(result, 32)
    np.asarray(resolved.overflow)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        resolved = resolve_jit(result, 32)
    np.asarray(resolved.overflow)
    resolve_time = (time.perf_counter() - t0) / args.iters

    baseline, native_baseline = _baselines_for(args.ops_per_doc, args.seed or 7)
    honest = native_baseline or baseline

    return {
        "metric": "crdt_ops_per_sec_per_chip",
        "value": round(device_ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(device_ops_per_sec / honest, 2),
        "baseline_ops_per_sec": round(honest, 1),
        "baseline_impl": "cpp-single-core-scalar-apply (native.pt_scalar_apply; "
                         "no node runtime in image for the TS reference)",
        "python_oracle_ops_per_sec": round(baseline, 1),
        "vs_python_oracle": round(device_ops_per_sec / baseline, 2),
        "docs": d,
        "ops_per_doc": k,
        "slot_capacity": s,
        "apply_seconds": round(best, 4),
        "single_call_seconds": round(single_call, 4),
        "resolve_seconds": round(resolve_time, 4),
        "compile_seconds": round(compile_time, 1),
        "workload_gen_seconds": round(gen_time, 1),
        "overflow_docs": overflow,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
    }


def _batch_ragged_tail(args, ops_dev, state0, apply_jit, sync, oracle,
                       total_ops, gen_time, padded_compile_s, *, d, s,
                       mark_cap, tomb_cap) -> dict:
    """layout=ragged variant of the batch row (ISSUE 12): the SAME synth
    streams apply through ops/ragged.py directly against a page pool — one
    compiled program for the whole batch, per-doc op/page counts as data —
    with the padded apply just computed as the byte-equality oracle, then
    the identical steady-state enqueue/sync protocol.  ``vs_baseline`` is
    measured in-row against the padded apply under the same protocol (one
    pass of ``--iters``), so the row gates the ragged/padded ratio, not
    two machines' clocks."""
    import jax
    import jax.numpy as jnp

    from peritext_tpu.ops.kernel import PAGED_AUX_FIELDS
    from peritext_tpu.ops.ragged import apply_batch_ragged_jit, plan_arrays
    from peritext_tpu.store import DEFAULT_PAGE_SIZE
    from peritext_tpu.store.paged import PagedDocStore
    from peritext_tpu.store.ragged import ragged_plan

    ins_counts = np.count_nonzero(np.asarray(ops_dev[1]), axis=1)
    del_counts = np.count_nonzero(np.asarray(ops_dev[3]), axis=1)
    max_pages = max(1, s // DEFAULT_PAGE_SIZE)
    need = np.minimum(
        -(-np.maximum(ins_counts, 1) // DEFAULT_PAGE_SIZE), max_pages
    )
    # pre-sized pool: growth mid-run would change the pool shape (an honest
    # recompile); sizing is the deployer's lever, shape stability the row's
    store = PagedDocStore(
        d, s, mark_cap, tomb_capacity=tomb_cap,
        initial_pages=1 + int(need.sum()),
    )
    rows = np.arange(d, dtype=np.int64)
    store.ensure_rows(rows, ins_counts)
    planes = plan_arrays(ragged_plan(store))
    ic_dev = jnp.asarray(ins_counts, jnp.int32)
    dc_dev = jnp.asarray(del_counts, jnp.int32)
    pool0 = (store.pool_elem, store.pool_char, store.aux)

    def apply_ragged():
        # nodonate: every dispatch re-applies the round to the SAME empty
        # pool, exactly as the padded loop re-applies to state0
        return apply_batch_ragged_jit(
            *pool0, *planes, ops_dev, ic_dev, dc_dev, donate=False,
        )

    ns_i = PAGED_AUX_FIELDS.index("num_slots")

    def sync_ragged(out):
        return np.asarray(out[2][ns_i])

    t0 = time.perf_counter()
    out = apply_ragged()
    sync_ragged(out)
    ragged_compile = time.perf_counter() - t0

    # byte equality, field by field: materialize the pool back to the
    # padded (D, S) view (widths match — S is a page multiple here, so
    # max_doc_pages * P == S) and compare against the padded oracle
    store.pool_elem, store.pool_char, store.aux = out
    got = store.materialize_rows(rows, bucket_pages=store.max_doc_pages)
    for f in oracle._fields:
        a = np.asarray(getattr(oracle, f))
        b = np.asarray(getattr(got, f))
        if f in ("elem_id", "char"):
            b = b[:, : a.shape[1]]
        assert np.array_equal(a, b), f"ragged apply diverged on {f}"
    overflow = int(np.asarray(got.overflow).sum())

    t0 = time.perf_counter()
    sync_ragged(apply_ragged())
    single_call = time.perf_counter() - t0

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = apply_ragged()
        sync_ragged(out)
        times.append(time.perf_counter() - t0)
    best = min(times) / args.iters
    value = total_ops / best

    # the in-row padded baseline: one pass of the same protocol (the full
    # 3-pass padded measurement is the batch_8k row's job, not this one's)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        res = apply_jit(state0, ops_dev)
    sync(res)
    padded_best = (time.perf_counter() - t0) / args.iters

    pool = store.pool_stats()
    return {
        "metric": "ragged_crdt_ops_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(padded_best / best, 2),
        "baseline_impl": "same synth batch through the padded (D, S) apply "
                         "(one pass of the same enqueue/sync protocol)",
        "baseline_ops_per_sec": round(total_ops / padded_best, 1),
        "byte_equal": True,
        "docs": d,
        "ops_per_doc": args.ops_per_doc,
        "slot_capacity": s,
        "apply_seconds": round(best, 4),
        "single_call_seconds": round(single_call, 4),
        "padded_apply_seconds": round(padded_best, 4),
        "compile_seconds": round(ragged_compile, 1),
        "padded_compile_seconds": round(padded_compile_s, 1),
        "overflow_docs": overflow,
        "page_pool": pool,
        "workload_gen_seconds": round(gen_time, 1),
        "platform": jax.devices()[0].platform,
    }


def build_arrival(workloads, rounds: int, seed, as_frames: bool = True,
                  arrival_model: str = "shuffle", wire: str = "v2"):
    """Per-doc round batches of a streaming session's arrival, split into
    ``rounds`` batches and — for the wire path — encoded per-sender
    sequential (senders flush their queues in order, changeQueue semantics;
    also what the wire codec's delta context expects).

    ``arrival_model``: "shuffle" (the r1-r3 bench shape: full random
    shuffle, i.e. per-sender REORDERING — a stress the real transport never
    produces, kept for record continuity and scheduling stress) or "fifo"
    (per-sender FIFO with random cross-sender interleave — what TCP + the
    reference's changeQueue actually deliver, src/changeQueue.ts:16-28).
    ``wire``: "v2" self-contained frames, or "v4" session-scoped frames
    (one WireSession per doc link: persistent string dictionary + deflate,
    codec.WireSession).

    SHARED by the end-to-end (run_streaming) and engine-limit (run_engine)
    rows: the engine row's whole value is being the same workload minus
    host cost, so the two must never drift apart.
    Returns (arrival, wire_bytes)."""
    import random

    from peritext_tpu.parallel.codec import WireSession, encode_frame

    rng = random.Random(seed)
    arrival = []
    wire_bytes = 0
    for w in workloads:
        if arrival_model == "fifo":
            logs = {a: list(l) for a, l in w.items()}
            actors = sorted(logs)
            changes = []
            while True:
                live = [a for a in actors if logs[a]]
                if not live:
                    break
                changes.append(logs[rng.choice(live)].pop(0))
        else:
            changes = [ch for log in w.values() for ch in log]
            rng.shuffle(changes)
        size = -(-len(changes) // rounds)
        batches = [changes[i : i + size] for i in range(0, len(changes), size)]
        if as_frames:
            enc = WireSession(compress=True).encode_frame if wire == "v4" \
                else encode_frame
            batches = [
                enc(sorted(b, key=lambda c: (c.actor, c.seq)))
                for b in batches
            ]
            wire_bytes += sum(len(b) for b in batches)
        arrival.append(batches)
    return arrival, wire_bytes


def run_streaming(args) -> dict:
    """BASELINE config 5: multi-round streaming merge on carried device state.

    Arrival batches are pre-encoded as binary wire frames (what a host
    actually receives over DCN, parallel/codec.py); ingestion takes the
    frame-native fast path (C++ parse + vectorized schedule/split,
    ops/frames.py) unless --object-ingest forces the Python object path."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.obs import GLOBAL_HISTOGRAMS, GLOBAL_TRACER
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    if args.trace_out:
        # pipeline spans for the measured sessions -> Perfetto JSON; render
        # a per-stage table with `python -m peritext_tpu.obs <trace>`
        GLOBAL_TRACER.enabled = True

    d, rounds = args.docs, args.rounds
    gen_start = time.perf_counter()
    workloads = generate_workload(seed=args.seed, num_docs=d, ops_per_doc=args.ops_per_doc)
    gen_time = time.perf_counter() - gen_start

    arrival, wire_bytes = build_arrival(
        workloads, rounds, args.seed, as_frames=not args.object_ingest
    )

    def session():
        return StreamingMerge(
            num_docs=d,
            actors=("doc1", "doc2", "doc3"),
            slot_capacity=args.slots,
            mark_capacity=args.marks,
            tomb_capacity=args.slots,
            round_insert_capacity=256,
            round_delete_capacity=128,
            round_mark_capacity=128,
        )

    def feed_round(s, r):
        if args.object_ingest:
            for doc, batches in enumerate(arrival):
                if r < len(batches):
                    s.ingest(doc, batches[r])
        else:
            # the bulk DCN receive path: one native parse call per round
            s.ingest_frames(
                (doc, batches[r])
                for doc, batches in enumerate(arrival)
                if r < len(batches)
            )

    def run_session():
        stages = {"ingest": 0.0, "schedule_apply": 0.0, "digest": 0.0}
        t_all = time.perf_counter()
        s = session()
        for r in range(rounds):
            t0 = time.perf_counter()
            feed_round(s, r)
            t1 = time.perf_counter()
            s.drain()
            t2 = time.perf_counter()
            stages["ingest"] += t1 - t0
            stages["schedule_apply"] += t2 - t1
        t0 = time.perf_counter()
        digest = s.digest()  # sync point: absorbs all queued device work
        stages["digest"] += time.perf_counter() - t0
        # host-parse share of the ingest stage (the C++ wire parse; the
        # rest of "ingest" is Python queue/bookkeeping) — VERDICT r4 task 3
        stages["host_parse"] = s.host_parse_seconds
        return time.perf_counter() - t_all, digest, stages, s

    # warmup compile
    _, digest0, _, s = run_session()
    fallbacks = sum(1 for sess in s.docs if sess.fallback)

    # tunnel dispatch latency is noisy: best of 3 timed sessions
    elapsed, stages = None, None
    for _ in range(3):
        t, digest, st, _ = run_session()
        assert digest == digest0
        if elapsed is None or t < elapsed:
            elapsed, stages = t, st

    total_ops = sum(
        len(ch.ops) for w in workloads for log in w.values() for ch in log
    )
    baseline, native_baseline = _baselines_for(args.ops_per_doc, args.seed or 7)
    honest = native_baseline or baseline
    value = total_ops / elapsed
    if args.trace_out:
        GLOBAL_TRACER.write_chrome_trace(args.trace_out)
    return {
        # rolling percentiles of the committed-round wall (schedule+apply
        # dispatch) across the whole measurement, the deadline-autotune view
        "round_latency": GLOBAL_HISTOGRAMS.get(
            "streaming.round_seconds"
        ).snapshot(),
        "metric": "streaming_crdt_ops_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(value / honest, 2),
        "baseline_ops_per_sec": round(honest, 1),
        "baseline_impl": "cpp-single-core-scalar-apply",
        "python_oracle_ops_per_sec": round(baseline, 1),
        "docs": d,
        "rounds": rounds,
        "ops_per_doc": args.ops_per_doc,
        "ingest": "objects" if args.object_ingest else "frames",
        "wire_bytes_per_op": round(wire_bytes / total_ops, 2) if wire_bytes else None,
        "fallback_docs": fallbacks,
        "workload_gen_seconds": round(gen_time, 1),
        "wall_seconds": round(elapsed, 3),
        "stage_seconds": {k: round(v, 3) for k, v in stages.items()},
        "platform": jax.devices()[0].platform,
    }


def run_streaming_fused(args) -> dict:
    """Fused device-resident round pipeline vs per-round dispatch (ISSUE 9).

    The SAME generated workload runs through two arms on identical session
    configs: (a) the FUSED pipeline — pipelined drain committing staged
    multi-round programs (one concatenated tensor set + one dispatch per
    batch, state donated where the platform profits, flatten+upload on the
    double-buffered staging lane) with the drain-end fused resolve+digest
    pre-dispatch; (b) the pre-fusion PER-ROUND dispatch discipline
    (``fused_pipeline=False`` compat switch: one compact apply dispatch per
    round, per-round staging, unpipelined).  Byte equality of spans,
    incremental patches and full-state digests is asserted IN-ROW on every
    seed measured (the fuzz-seed oracle); the row's value is the fused
    arm's throughput.  Round caps sit below the streaming row's so each
    drain carries a genuinely multi-round queue — the scenario the fused
    dispatch exists for."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    d, rounds = args.docs, args.rounds
    gen_start = time.perf_counter()
    workloads = generate_workload(seed=args.seed, num_docs=d,
                                  ops_per_doc=args.ops_per_doc)
    gen_time = time.perf_counter() - gen_start
    arrival, _ = build_arrival(workloads, rounds, args.seed)
    total_ops = sum(
        len(ch.ops) for w in workloads for log in w.values() for ch in log
    )

    def session(fused: bool, prefetch: bool):
        s = StreamingMerge(
            num_docs=d,
            actors=("doc1", "doc2", "doc3"),
            slot_capacity=args.slots,
            mark_capacity=args.marks,
            tomb_capacity=args.slots,
            round_insert_capacity=48,
            round_delete_capacity=24,
            round_mark_capacity=24,
            round_map_capacity=12,
        )
        s.fused_pipeline = fused
        # the drain-end digest pre-dispatch pays off when reads/digests
        # follow EVERY drain (the serving pump — measured by the serve
        # row); this row digests once at the end, so the measured arm runs
        # prefetch off while the equality arms keep it on (its semantic
        # parity is part of the in-row oracle)
        s.prefetch_digest = fused and prefetch
        return s

    def run_arm(fused: bool, this_arrival=None, prefetch: bool = True):
        batches = this_arrival if this_arrival is not None else arrival
        s = session(fused, prefetch)
        stages = {"ingest": 0.0, "drain": 0.0, "digest": 0.0}
        t_all = time.perf_counter()
        for r in range(len(max(batches, key=len))):
            t0 = time.perf_counter()
            s.ingest_frames(
                (doc, b[r]) for doc, b in enumerate(batches) if r < len(b)
            )
            t1 = time.perf_counter()
            if fused:
                s.drain()
            else:
                while s.step() > 0:  # the per-round dispatch discipline
                    pass
            stages["ingest"] += t1 - t0
            stages["drain"] += time.perf_counter() - t1
        t0 = time.perf_counter()
        digest = s.digest()
        stages["digest"] += time.perf_counter() - t0
        return time.perf_counter() - t_all, digest, stages, s

    # warmup (compiles) + the measured seed's byte-equality assertion:
    # spans, incremental patches, digests — fused vs per-round
    _, dg_f, _, s_f = run_arm(True)
    _, dg_p, _, s_p = run_arm(False)
    assert dg_f == dg_p, f"fused digest {dg_f:#x} != per-round {dg_p:#x}"
    assert s_f.rounds == s_p.rounds
    assert s_f.read_all() == s_p.read_all()
    assert s_f.read_patches_all() == s_p.read_patches_all()
    fused_rounds = s_f.rounds

    # extra fuzz seeds: the equivalence must hold beyond the measured seed
    equality_seeds = [args.seed]
    for extra in (args.seed + 1, args.seed + 2):
        wl = generate_workload(seed=extra, num_docs=min(d, 16),
                               ops_per_doc=min(args.ops_per_doc, 64))
        arr, _ = build_arrival(wl, max(2, rounds // 2), extra)
        _, dg_a, _, sa = run_arm(True, arr)
        _, dg_b, _, sb = run_arm(False, arr)
        assert dg_a == dg_b, f"seed {extra}: fused/per-round digests differ"
        assert sa.read_all() == sb.read_all()
        equality_seeds.append(extra)

    def best_of(fused: bool):
        # the row's stager counters come from the BEST MEASURED run, so
        # the overlap accounting describes the execution whose wall the
        # row reports (not the prefetch-on warmup/equality arm)
        best, best_stages, best_stager, dg0 = None, None, None, None
        for _ in range(3):
            t, dg, st, sess = run_arm(fused, prefetch=False)
            if dg0 is None:
                dg0 = dg
            assert dg == dg0
            if best is None or t < best:
                best, best_stages = t, st
                best_stager = (sess._stager.stats()
                               if sess._stager is not None else None)
        return best, best_stages, best_stager

    fused_wall, fused_stages, stager_stats = best_of(True)
    per_round_wall, _, _ = best_of(False)

    baseline, native_baseline = _baselines_for(args.ops_per_doc, args.seed or 7)
    honest = native_baseline or baseline
    value = total_ops / fused_wall
    per_round_value = total_ops / per_round_wall
    return {
        "metric": "streaming_fused_crdt_ops_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(value / honest, 2),
        "baseline_ops_per_sec": round(honest, 1),
        "baseline_impl": "cpp-single-core-scalar-apply",
        "per_round_ops_per_sec": round(per_round_value, 1),
        "speedup_vs_per_round": round(value / per_round_value, 2),
        "byte_equal_seeds": equality_seeds,
        "docs": d,
        "rounds": rounds,
        "device_rounds": fused_rounds,
        "ops_per_doc": args.ops_per_doc,
        "workload_gen_seconds": round(gen_time, 1),
        "wall_seconds": round(fused_wall, 3),
        "per_round_wall_seconds": round(per_round_wall, 3),
        "stage_seconds": {k: round(v, 3) for k, v in fused_stages.items()},
        "stager": stager_stats,
        "platform": jax.devices()[0].platform,
    }


def _run_bounded(argv, timeout, env=None):
    """Run argv in its own session under a hard timeout; SIGKILL the whole
    process group on expiry (a plain terminate can leave tunnel threads
    holding the pipe open).  Returns (rc, stdout, stderr); rc is None on
    timeout."""
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        return None, out, err


def probe_device(timeout=PROBE_TIMEOUT, attempts=PROBE_ATTEMPTS,
                 backoff=PROBE_BACKOFF):
    """Bounded-timeout TPU/default-backend probe with retries.

    Returns (platform | None, error_tail).  platform is the default jax
    backend's platform name when init + one device round-trip succeed within
    the timeout; None means every attempt hung or failed (error_tail carries
    the last stderr/stdout tail for the evidence record)."""
    tail = ""
    for attempt in range(attempts):
        if attempt:
            time.sleep(backoff)
        rc, out, err = _run_bounded([sys.executable, "-c", _PROBE_CODE], timeout)
        for line in out.splitlines():
            if line.startswith("PROBE_OK"):
                return line.split()[1], ""
        status = "timed out" if rc is None else f"rc={rc}"
        tail = f"probe attempt {attempt + 1}/{attempts} {status}: " + (
            (err or out).strip()[-1500:]
        )
        print(f"bench: {tail}", file=sys.stderr)
    return None, tail


def _parse_json_tail(out):
    """Last stdout line that parses as a JSON object (jax warnings precede it)."""
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _worker_argv(extra):
    return [sys.executable, os.path.abspath(__file__), "--_worker", *extra]


def _append_ledger(path, rows, config, platform, devprof=None):
    """Append one perf-ledger record (obs/ledger.py) built from bench rows.

    Device fingerprinting here must NOT import jax — the orchestrator
    process deliberately never initializes a backend (a dead axon tunnel
    hangs it) — so the key is the measured rows' platform + host cores."""
    from peritext_tpu.obs import ledger as _ledger

    device = {"platform": platform, "kind": platform, "cpus": os.cpu_count()}
    record = _ledger.ledger_record(
        rows, config=config, devprof=devprof, device=device,
    )
    try:
        _ledger.append_record(path, record)
    except OSError as exc:  # an unwritable ledger must not cost the record
        print(f"bench: perf-ledger append failed: {exc}", file=sys.stderr)
        return
    print(f"bench: appended perf-ledger record ({len(rows)} row(s)) -> {path}",
          file=sys.stderr)


def orchestrate(args, passthrough) -> int:
    """Probe → run worker under timeout → always print one JSON line.

    Exit 0 whenever a measurement (TPU or CPU-fallback) was recorded; exit 1
    only if even the CPU path failed — and still print a structured JSON
    line with the error tail so the driver's record stays parseable."""
    extras = {}
    if args.platform:
        platform = args.platform  # explicit: trust the caller, no probe
    else:
        t0 = time.perf_counter()
        platform, probe_tail = probe_device()
        extras["probe_seconds"] = round(time.perf_counter() - t0, 1)
        if platform is None:
            extras["tpu_unavailable"] = True
            extras["tpu_error"] = probe_tail
            platform = "cpu"
        elif platform == "cpu":
            # default backend is already cpu: no TPU plugin in this env
            extras["tpu_unavailable"] = True
            extras["tpu_error"] = "default jax backend is cpu (no TPU plugin attached)"

    attempts_left = 2 if platform not in (None, "cpu") else 1
    while True:
        # Pin the platform only for the cpu fallback (or an explicit user
        # choice): the axon plugin registers backend name "axon" but reports
        # device platform "tpu", so re-pinning the probed name could miss —
        # the worker should init the default backend exactly as the probe did.
        if platform == "cpu" or args.platform:
            worker_args = [*passthrough, "--platform", platform]
        else:
            worker_args = list(passthrough)
        if platform == "cpu" and extras.get("tpu_unavailable") and not args.smoke \
                and args.docs is None and args.ops_per_doc is None:
            # CPU fallback of a full-size TPU config would run for tens of
            # minutes; record the smoke config instead, and say so.
            worker_args.append("--smoke")
            extras["fallback_config"] = "smoke"
        rc, out, err = _run_bounded(_worker_argv(worker_args), WORKER_TIMEOUT)
        result = _parse_json_tail(out)
        if rc == 0 and result is not None:
            result.update(extras)
            print(json.dumps(result))
            if args.ledger:
                row = dict(result)
                devprof = row.pop("devprof", None)
                row.setdefault("row", args.mode)
                # the EFFECTIVE sizing, not the requested one: the CPU
                # fallback silently reruns the smoke config, and recording
                # it under the full-run config would split one history in
                # two and fire spurious `missing` verdicts
                smoke = args.smoke or extras.get("fallback_config") == "smoke"
                _append_ledger(
                    args.ledger, [row],
                    config=args.mode + ("-smoke" if smoke else ""),
                    platform=row.get("platform") or platform,
                    devprof={row["row"]: devprof} if devprof else None,
                )
            return 0
        status = "timed out" if rc is None else f"rc={rc}"
        tail = (err or out).strip()[-1500:]
        print(f"bench: worker on {platform} {status}: {tail}", file=sys.stderr)
        attempts_left -= 1
        if attempts_left > 0:
            continue
        if platform != "cpu":
            # TPU passed the probe but the measurement died: fall back
            extras["tpu_unavailable"] = True
            extras["tpu_error"] = f"worker on {platform} {status}: {tail}"
            platform = "cpu"
            attempts_left = 1
            continue
        # even CPU failed — structured failure record, nonzero exit
        metric_of_mode = {
            "streaming": "streaming_crdt_ops_per_sec_per_chip",
            "engine": "engine_limit_streaming_ops_per_sec_per_chip",
            "batch": "crdt_ops_per_sec_per_chip",
            "serve": "serve_sustained_docs_per_sec",
            "serve-fused": "serve_multitenant_dispatch_amortization",
            "mesh": "mesh_sustained_ops_per_sec",
            "storm": "reconnect_storm_drain_ops_per_sec",
            "longdoc": "longdoc_ragged_ops_per_sec",
            "markheavy": "markheavy_ops_per_sec",
            "fleet-serve": "fleet_serve_applied_frames_per_sec",
        }
        print(json.dumps({
            "metric": metric_of_mode.get(args.mode, "crdt_ops_per_sec_per_chip"),
            "value": None,
            "unit": "ops/s",
            "vs_baseline": None,
            "failed": True,
            "error": f"worker on cpu {status}: {tail}",
            **extras,
        }))
        return 1


def run_engine(args) -> dict:
    """Engine-limit streaming measurement (round-3 VERDICT item 3; round-5
    steady-state redefinition, VERDICT r4 task 2).

    The end-to-end streaming row is bounded by the host link (parse +
    transfer + dispatch latency); this mode measures the ENGINE itself: a
    real streaming session runs once with round capture enabled, recording
    every round's device-ready op streams, then the replay times pure
    device work — K chained apply programs plus the fused full-state digest
    — with zero host parse/schedule/transfer per round.

    Two numbers, mirroring the batch row's apply_seconds vs
    single_call_seconds split: the HEADLINE is steady-state throughput
    (several replay passes enqueued back-to-back, one sync — what a
    continuously-fed engine sustains, the per-measurement tunnel round trip
    ~0.1 s amortized away), and ``engine_pass_seconds`` is the single-pass
    latency including that round trip (what one isolated
    ingest->converge->digest costs).  Round-5 attribution measured the old
    single-pass number as ~1/3 fixed tunnel RTT (scripts/engine_profile.py
    --fine), which is a property of the link, not the engine."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from peritext_tpu.ops.kernel import apply_batch_compact_rounds_jit
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.parallel.streaming import (
        StreamingMerge, _resolve_block_digest_jit,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    d, rounds = args.docs, args.rounds
    workloads = generate_workload(seed=args.seed, num_docs=d, ops_per_doc=args.ops_per_doc)
    arrival, _ = build_arrival(workloads, rounds, args.seed)

    def session(capture=None):
        s = StreamingMerge(
            num_docs=d,
            actors=("doc1", "doc2", "doc3"),
            slot_capacity=args.slots,
            mark_capacity=args.marks,
            tomb_capacity=args.slots,
            round_insert_capacity=256,
            round_delete_capacity=128,
            round_mark_capacity=128,
        )
        s._capture_rounds = capture
        t0 = time.perf_counter()
        for r in range(rounds):
            s.ingest_frames(
                (doc, batches[r]) for doc, batches in enumerate(arrival)
                if r < len(batches)
            )
            s.drain()
        digest = s.digest()
        return s, digest, time.perf_counter() - t0

    captured: list = []
    s, expected_digest, _ = session(captured)  # warmup run (compiles) + capture
    _, digest2, end_to_end = session()  # warm end-to-end reference
    assert digest2 == expected_digest, "end-to-end sessions disagree"
    assert not any(sess.fallback for sess in s.docs), \
        "fallback docs would skew the engine row (raise capacities)"
    # overflowed docs are hashed HOST-side by digest() but masked in the
    # device-only replay sum — they would break the digest cross-check below
    assert s.overflow_count() == 0, \
        f"{s.overflow_count()} docs overflowed device capacities (raise --slots/--marks)"

    # replay: pre-stage everything device-side, then chain the rounds.
    # The captured rounds are _padded_docs-shaped (meshless sessions pad to
    # a read-block multiple), so the replay state must match.
    state0 = empty_docs(s._padded_docs, args.slots, args.marks,
                        tomb_capacity=args.slots)
    state0 = jax.device_put(state0)
    staged = [
        ((tuple(jax.device_put(np.asarray(c)) for c in counts),
          ins, dels, marks, maps), widths, loop_slots)
        for (counts, ins, dels, marks, maps), widths, loop_slots in captured
    ]
    tables = s._digest_tables(0, s._padded_docs)
    row_mask = jnp.ones(s._padded_docs, bool)

    def engine_pass_async():
        """Dispatch one full replay (rounds fused in FUSE_MAX_ROUNDS
        chunks, exactly as the live drain() fuses a deep queue, plus the
        fused resolve/digest); returns the device per-doc hash vector
        WITHOUT syncing."""
        fmax = StreamingMerge.FUSE_MAX_ROUNDS
        st = state0
        for lo in range(0, len(staged), fmax):
            part = staged[lo:lo + fmax]
            st = apply_batch_compact_rounds_jit(
                st, [r[0] for r in part],
                widths_seq=[r[1] for r in part],
                loop_slots_seq=[r[2] for r in part],
            )
        _, per_doc = _resolve_block_digest_jit(
            st, s.comment_capacity, row_mask, *tables
        )
        return per_doc

    def digest_of(per_doc):
        # the sync point (per-doc hash vector; block sum = digest)
        return int(np.asarray(per_doc).sum(dtype=np.uint32))

    warm = digest_of(engine_pass_async())  # warmup + correctness
    assert warm == expected_digest, \
        f"engine replay digest {warm:#x} != live session {expected_digest:#x}"
    # single-pass latency: dispatch -> converged digest on host, incl. the
    # fixed per-measurement link round trip
    lat_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        digest = digest_of(engine_pass_async())
        lat_times.append(time.perf_counter() - t0)
    assert digest == expected_digest, "engine replay digest drifted across passes"
    latency = min(lat_times)

    # steady-state: enqueue several independent replay passes back-to-back
    # (the device executes queued programs serially) and sync ONLY the
    # last pass inside the clock — it completes after all queued
    # predecessors, so the timed region holds one link round trip, not
    # one per pass; every pass's digest is verified after the clock stops
    passes = max(2, int(args.iters) // 2)
    t0 = time.perf_counter()
    per_docs = [engine_pass_async() for _ in range(passes)]
    last_digest = digest_of(per_docs[-1])
    steady = (time.perf_counter() - t0) / passes
    digests = [digest_of(p) for p in per_docs[:-1]] + [last_digest]
    assert all(g == expected_digest for g in digests), \
        "steady-state engine pass diverged"

    total_ops = sum(
        len(ch.ops) for w in workloads for log in w.values() for ch in log
    )
    value = total_ops / steady
    return {
        "metric": "engine_limit_streaming_ops_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(value / (total_ops / end_to_end), 2),
        "baseline_impl": "same session end-to-end (host parse + transfer + dispatch)",
        "end_to_end_ops_per_sec": round(total_ops / end_to_end, 1),
        "single_pass_ops_per_sec": round(total_ops / latency, 1),
        "docs": d,
        "rounds": len(staged),
        "ops_per_doc": args.ops_per_doc,
        "steady_passes": passes,
        "engine_wall_seconds": round(steady, 3),
        "engine_pass_seconds": round(latency, 3),
        "end_to_end_wall_seconds": round(end_to_end, 3),
        "platform": jax.devices()[0].platform,
    }


def run_baselines(args) -> dict:
    """Scalar baselines row (BASELINE config 1): the pure-Python oracle and
    the C++ single-core apply, measured once per ladder and shared with the
    other rows via PT_BENCH_BASELINES."""
    python = measure_scalar_baseline()
    native = measure_native_baseline(ops_per_doc=256, seed=7)
    return {
        "metric": "baseline_ops_per_sec",
        "value": round(native or python, 1),
        "unit": "ops/s",
        "vs_baseline": 1.0,
        "baseline_impl": "cpp-single-core-scalar-apply" if native
                         else "python-scalar-oracle",
        "scalar_python_ops_per_sec": round(python, 1),
        "native_cpp_ops_per_sec": round(native, 1) if native else None,
        "native_ops_per_doc": 256,
        "platform": "cpu",
    }


def run_wire(args) -> dict:
    """Wire-efficiency row: bytes/op of the binary frame codec on the three
    shapes the round-3 analysis tracks (VERDICT r3 weak #4) — interactive
    typing, a causal fuzz session, and the streaming bench's arrival frames
    — each against the reference's JSON-per-change wire
    (src/micromerge.ts:563-564) as the compression baseline.  Each shape is
    measured self-contained (v2) and through a session-scoped WireSession
    (v4: persistent string dictionary + deflate, VERDICT r3 task 3).
    Host-only: no device work, so the row is platform-independent."""
    from peritext_tpu.core.doc import Doc
    from peritext_tpu.parallel.causal import causal_sort
    from peritext_tpu.parallel.codec import WireSession, decode_frame, encode_frame
    from peritext_tpu.testing.fuzz import generate_workload

    def json_bytes(chs):
        return sum(len(json.dumps(c.to_json()).encode()) for c in chs)

    def session_bytes(frame_batches):
        """Total v4 bytes: one WireSession per link, frames in order."""
        enc = WireSession(compress=True)
        dec = WireSession(compress=True)
        total = 0
        for chs in frame_batches:
            f = enc.encode_frame(chs)
            assert dec.decode_frame(f) == chs
            total += len(f)
        return total

    shapes = {}

    # typing shape: 20 multi-char inserts (the reference's chained-op path)
    d = Doc("alice")
    chs = [d.change([{"path": [], "action": "makeList", "key": "text"}])[0]]
    text = "The quick brown fox jumps over the lazy dog. " * 20
    pos = 0
    for i in range(20):
        seg = text[i * 45:(i + 1) * 45]
        chs.append(d.change([{"path": ["text"], "action": "insert",
                              "index": pos, "values": list(seg)}])[0])
        pos += len(seg)
    f = encode_frame(chs)
    assert decode_frame(f) == chs
    n = sum(len(c.ops) for c in chs)
    shapes["typing"] = {
        "bytes_per_op": round(len(f) / n, 2),
        "session_bytes_per_op": round(session_bytes([chs]) / n, 2),
        "json_bytes_per_op": round(json_bytes(chs) / n, 2),
        "ops": n,
    }

    # fuzz-session shape: causally-ordered 3-replica session logs
    tot_b = tot_o = tot_j = tot_s = 0
    for wl in generate_workload(seed=21, num_docs=3, ops_per_doc=140):
        sess = causal_sort([ch for log in wl.values() for ch in log])
        f = encode_frame(sess)
        assert decode_frame(f) == sess
        tot_b += len(f)
        tot_s += session_bytes([sess])
        tot_j += json_bytes(sess)
        tot_o += sum(len(c.ops) for c in sess)
    shapes["fuzz_session"] = {
        "bytes_per_op": round(tot_b / tot_o, 2),
        "session_bytes_per_op": round(tot_s / tot_o, 2),
        "json_bytes_per_op": round(tot_j / tot_o, 2),
        "ops": tot_o,
    }

    # streaming-bench shape: the arrival frames the streaming row pays, in
    # both arrival models (shuffle = r1-r3 record continuity; fifo = what
    # TCP + changeQueue actually deliver) and both wire generations
    docs = args.docs
    workloads = generate_workload(seed=args.seed, num_docs=docs, ops_per_doc=192)
    total_ops = sum(len(c.ops) for w in workloads for log in w.values() for c in log)
    sample_json = sum(
        json_bytes([c for log in w.values() for c in log]) for w in workloads[:32]
    )
    sample_ops = sum(
        len(c.ops) for w in workloads[:32] for log in w.values() for c in log
    )
    variants = {}
    for model in ("shuffle", "fifo"):
        for wire in ("v2", "v4"):
            _, wb = build_arrival(workloads, rounds=4, seed=args.seed,
                                  arrival_model=model, wire=wire)
            variants[f"{model}_{wire}"] = round(wb / total_ops, 2)
    # host-link model: a DCN link between two hosts muxes EVERY doc's frames
    # through one WireSession (per-doc sessions above are the conservative
    # bound — real deployments share the link dictionary + deflate window)
    from peritext_tpu.parallel.codec import WireSession as _WS

    batches, _ = build_arrival(workloads, rounds=4, seed=args.seed,
                               as_frames=False, arrival_model="fifo")
    enc, dec = _WS(compress=True), _WS(compress=True)
    link_bytes = 0
    for r in range(4):
        for doc_batches in batches:
            if r < len(doc_batches):
                b = sorted(doc_batches[r], key=lambda c: (c.actor, c.seq))
                f = enc.encode_frame(b)
                assert dec.decode_frame(f) == b
                link_bytes += len(f)
    variants["fifo_v4_host_link"] = round(link_bytes / total_ops, 2)
    # per-doc links with the protocol preset dictionary (codec.WireSession
    # preset=True): a fresh link's deflate window is primed so first frames
    # back-reference the dictionary the way a warm link references its own
    # window — the per-doc-link answer to the <=6 target (VERDICT r4 task 8)
    preset_bytes = 0
    for doc_batches in batches:
        enc = _WS(compress=True, preset=True)
        dec = _WS(compress=True, preset=True)
        for b in doc_batches:
            b = sorted(b, key=lambda c: (c.actor, c.seq))
            f = enc.encode_frame(b)
            assert dec.decode_frame(f) == b
            preset_bytes += len(f)
    variants["fifo_v4_preset"] = round(preset_bytes / total_ops, 2)
    shapes["bench_frames"] = {
        "bytes_per_op": variants["shuffle_v2"],   # r1-r3 continuity number
        "variants_bytes_per_op": variants,
        "session_bytes_per_op": variants["fifo_v4_host_link"],  # real transport
        "json_bytes_per_op": round(sample_json / sample_ops, 2),
        "ops": total_ops,
        "docs": docs,
    }

    headline = shapes["bench_frames"]["session_bytes_per_op"]
    return {
        "metric": "wire_bytes_per_op",
        "value": headline,
        "unit": "B/op",
        # vs the JSON wire: how many times smaller the binary frames are
        "vs_baseline": round(shapes["bench_frames"]["json_bytes_per_op"] / headline, 2),
        "baseline_impl": "json-encoded changes (reference wire, src/micromerge.ts:563)",
        "shapes": shapes,
        "platform": "host",
    }


def run_fleet_heal(args) -> dict:
    """Fleet-heal row (ISSUE 4): time-to-convergence and ops drained per
    second after a simulated partition heal.  Drives the chaos fleet
    harness (``testing/chaos.run_fleet_chaos``): an N-host ReplicaServer
    fleet diverges under an asymmetric partition, then the gossip
    scheduler's most-behind-first rounds drain it; the row reports how fast
    the anti-entropy layer re-converges the fleet.  Host-only (TCP +
    codec + store work, no device), so the row is platform-independent."""
    from peritext_tpu.testing.chaos import run_fleet_chaos

    hosts = 3 if args.smoke else 4
    reports = []
    for i in range(max(1, min(args.iters, 3))):
        reports.append(run_fleet_chaos(args.seed + i, hosts=hosts,
                                       metrics=False))
    best = max(reports, key=lambda r: r.ops_drained / max(r.heal_seconds, 1e-9))
    rate = best.ops_drained / max(best.heal_seconds, 1e-9)
    return {
        "metric": "fleet_heal_ops_per_sec",
        "value": round(rate, 1),
        "unit": "ops/s",
        "baseline_impl": "asymmetric-partition heal over localhost TCP gates",
        "hosts": hosts,
        "episodes": len(reports),
        "time_to_convergence_s": round(best.heal_seconds, 4),
        "heal_rounds": best.heal_rounds,
        "ops_drained": best.ops_drained,
        "partition_lag_ops": sum(best.expected_lag.values()),
        "converged": all(r.converged for r in reports),
        "platform": "host",
    }


def run_serve(args) -> dict:
    """Serving-tier row (ISSUE 7): sustained OPEN-LOOP traffic ladder.

    Drives a :class:`~peritext_tpu.serve.SessionMux` (admission control +
    autotuned round window over a streaming session) with an open-loop
    arrival schedule — arrival times fixed by the offered rate, never by
    service completions — sweeping the rate upward until the p99
    apply-latency SLO breaks or verdicts stop being clean.  The headline is
    docs/s at the SLO (each arrival is one session's frame), the breakdown
    rung is recorded too, and the typed-verdict accounting plus the
    autotuned window land in the row for the serve exporters' story."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.serve import (
        AdmissionController, SessionMux, sustained_ladder,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    d = args.docs
    slo_s = args.serve_slo_ms / 1e3
    workloads = generate_workload(seed=args.seed + 11, num_docs=d,
                                  ops_per_doc=args.ops_per_doc)
    frame_plans = []
    for w in workloads:
        changes = [ch for log in w.values() for ch in log]
        frame_plans.append([
            encode_frame(changes[i:i + 6])
            for i in range(0, len(changes), 6)
        ])

    def serve_session():
        # static_rounds: the serving-tier shape discipline — one padded
        # apply shape for the session's lifetime, so an arrival pattern
        # can never mint an XLA compile inside a client's p99
        opd = args.ops_per_doc
        return StreamingMerge(
            num_docs=d, actors=("doc1", "doc2", "doc3"),
            slot_capacity=max(256, 4 * opd), mark_capacity=max(64, opd),
            tomb_capacity=max(128, opd),
            round_insert_capacity=128, round_delete_capacity=64,
            round_mark_capacity=64,
            static_rounds=True,
        )

    def mux_factory():
        mux = SessionMux(
            serve_session(),
            admission=AdmissionController(
                max_depth=max(256, 4 * d), session_quota=None,
            ),
            host="bench",
        )
        frames = {}
        for doc in range(d):
            sid, verdict = mux.open_session(f"client{doc}")
            assert verdict.admitted
            frames[sid] = frame_plans[doc]
        return mux, frames

    # warmup: compile the apply/digest programs OUTSIDE the measured rungs.
    # Trickle rounds pick ADAPTIVE power-of-two round widths (streaming's
    # shape discipline), so each distinct batch size class can mint a new
    # XLA variant — walk the batch-size ladder once so no rung pays a
    # compile inside its latency percentile.
    mux, frames = mux_factory()
    sids = sorted(frames)
    cursor = {sid: 0 for sid in sids}
    batch_size = 1
    while batch_size <= 2 * d:
        for i in range(batch_size):
            sid = sids[i % len(sids)]
            plan = frames[sid]
            mux.submit(sid, plan[cursor[sid] % len(plan)])
            cursor[sid] += 1
        mux.flush()
        batch_size *= 2

    base = 25.0 if args.smoke else 50.0
    rates = [base * (2 ** i) for i in range(11 if args.smoke else 12)]
    duration = 0.5 if args.smoke else 1.5
    rungs, best = sustained_ladder(
        mux_factory, rates, slo_p99_s=slo_s, duration_s=duration,
        warmup=2,
    )
    broke = next((r for r in rungs if not r.sustained), None)
    if best is not None and broke is not None:
        # refine between the last sustained and the breaking rung: the x2
        # sweep quantizes the headline to a factor of two, which is wider
        # than the perf ledger's wall-clock band — one midpoint rung
        # tightens resolution to x1.5
        mid_rungs, mid_best = sustained_ladder(
            mux_factory, [best.rate_per_s * 1.5], slo_p99_s=slo_s,
            duration_s=duration, warmup=1,
        )
        rungs.extend(mid_rungs)
        if mid_best is not None:
            best = mid_best
    value = best.rate_per_s if best is not None else 0.0

    # traced pass: one extra sustained-rate rung on a fresh mux with the
    # latency plane armed — OUTSIDE the measured ladder, so arming cost
    # can never touch the headline.  read_every marks visibility, so the
    # row's decomposition carries the full admit→visibility story, and
    # the sum-consistency oracle is asserted IN-ROW.
    from peritext_tpu.obs.latency import LatencyPlane
    from peritext_tpu.obs.timeseries import TimeSeriesPlane
    from peritext_tpu.serve import build_arrivals, run_open_loop

    tmux, tframes = mux_factory()
    tmux.latency_plane = LatencyPlane().enable()
    # the history plane rides the same traced rung: one retained frame
    # per settled batch, so the row carries the trend view's raw feed
    hist = TimeSeriesPlane(sample_every=1, min_frames=4)
    tmux.history_plane = hist.enable()
    trace_rate = max(base, value / 2.0) if value else base
    traced = run_open_loop(
        tmux, build_arrivals(tframes, trace_rate, duration),
        deadline_s=max(duration * 4, duration + 2.0), read_every=4,
    )
    lat = traced.latency
    assert lat is not None and lat["records"] > 0, (
        "armed latency plane sampled no drain batches in the traced rung"
    )
    assert lat["sum_consistent"], f"latency decomposition inconsistent: {lat}"
    assert all(v >= 0 for v in lat["stages_ms"].values()), (
        f"negative stage duration: {lat['stages_ms']}"
    )
    assert hist.frames_sampled > 0, (
        "armed history plane retained no frames in the traced rung"
    )

    return {
        "metric": "serve_sustained_docs_per_sec",
        "value": round(value, 1),
        "unit": "docs/s",
        "vs_baseline": None,
        "baseline_impl": "open-loop arrival ladder vs p99 apply-latency SLO",
        "slo_p99_ms": args.serve_slo_ms,
        "docs": d,
        "ops_per_doc": args.ops_per_doc,
        "sessions": d,
        "rung_duration_s": duration,
        "sustained_rung": best.to_json() if best is not None else None,
        "breaking_rung": broke.to_json() if broke is not None else None,
        # every offered rate sustained: the true ceiling is above the sweep
        "ladder_exhausted": broke is None,
        "latency": lat,
        "history": {
            "frames_sampled": hist.frames_sampled,
            "frames_retained": sum(hist.snapshot()["tier_frames"]),
            "rounds": hist.rounds,
            "anomalies_total": hist.anomalies_total,
        },
        "traced_rate_per_s": round(trace_rate, 1),
        "rungs": [r.to_json() for r in rungs],
        "window": (best.result.window_seconds if best is not None else None),
        "platform": jax.devices()[0].platform,
    }


def run_serve_fused(args) -> dict:
    """Multi-tenant fused-dispatch row (ISSUE 13): N small tenants served
    through ONE :class:`~peritext_tpu.serve.FusedMuxGroup` lane vs N
    standalone per-session muxes, same frames, same windows.

    The fused arm commits each batching window as one staged device
    program per touched lane (the plan tier's
    :class:`~peritext_tpu.plan.fusion.FusionGroup` assigns disjoint
    doc-row ranges; sparse windows ride the multi-tenant offset-plane
    staged form); the per-session arm drains every tenant separately —
    the dispatch-floor bill this row exists to show.  Byte equality of
    every tenant's patch stream against its standalone twin is asserted
    IN-ROW (the CRDT correctness oracle), and both arms' p99 apply
    latencies ride along.  Headline = device programs per window saved:
    per-session dispatches / fused dispatches."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.obs import GLOBAL_COUNTERS
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.plan.fusion import TenantSpec
    from peritext_tpu.serve import (
        FusedMuxGroup, SessionMux, default_lane_factory,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    tenants_n = args.docs  # one small tenant per doc slot
    opd = args.ops_per_doc
    actors = ("doc1", "doc2", "doc3")
    windows = 6
    workloads = generate_workload(seed=args.seed + 13, num_docs=tenants_n,
                                  ops_per_doc=opd)
    names = [f"tenant{i:03d}" for i in range(tenants_n)]
    frame_plans = {}
    for name, w in zip(names, workloads):
        changes = sorted((ch for log in w.values() for ch in log),
                         key=lambda c: (c.actor, c.seq))
        frame_plans[name] = [
            encode_frame(changes[i::windows]) for i in range(windows)
        ]
    # window plan: alternating full and sparse activity — the sparse
    # windows exercise the multi-tenant offset-plane staged form (only
    # the active tenants' doc blocks ship), the full ones the shared
    # full-lane staging.  Every tenant's frames stay in causal order.
    active_of = []
    cursor = {n: 0 for n in names}
    for w in range(windows):
        if w % 2 == 0:
            active_of.append(list(names))
        else:
            active_of.append(names[(w // 2) % 4::4])
    plan = []  # (window, tenant, frame)
    for w, active in enumerate(active_of):
        step = []
        for n in active:
            if cursor[n] < windows:
                step.append((n, frame_plans[n][cursor[n]]))
                cursor[n] += 1
        plan.append(step)
    # leftover frames drain in a final full window
    tail = [(n, frame_plans[n][c])
            for n in names for c in range(cursor[n], windows)]
    if tail:
        plan.append(tail)

    session_kw = dict(
        slot_capacity=max(256, 4 * opd), mark_capacity=max(64, opd),
        tomb_capacity=max(128, opd),
        round_insert_capacity=128, round_delete_capacity=64,
        round_mark_capacity=64,
    )

    def build_group():
        group = FusedMuxGroup(
            [TenantSpec(tenant=n, docs=1) for n in names],
            default_lane_factory(actors, **session_kw),
            host="bench-fused",
        )
        sids = {}
        for n in names:
            sid, verdict = group.open_session(n, "client")
            assert verdict.admitted
            sids[n] = sid
            group.muxes[n].latency_sink = []
        return group, sids

    def build_solo():
        muxes, sids = {}, {}
        for n in names:
            mux = SessionMux(
                StreamingMerge(num_docs=1, actors=actors,
                               static_rounds=True, **session_kw),
                host="bench-solo",
            )
            sid, verdict = mux.open_session("client")
            assert verdict.admitted
            muxes[n], sids[n] = mux, sid
            mux.latency_sink = []
        return muxes, sids

    def drive_group(group, sids):
        d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
        t0 = time.perf_counter()
        for step in plan:
            for n, frame in step:
                verdict = group.submit(n, sids[n], frame)
                assert verdict.admitted, verdict
            group.flush()
        wall = time.perf_counter() - t0
        return (int(GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0),
                wall)

    def drive_solo(muxes, sids):
        d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
        t0 = time.perf_counter()
        for step in plan:
            touched = []
            for n, frame in step:
                verdict = muxes[n].submit(sids[n], frame)
                assert verdict.admitted, verdict
                touched.append(n)
            for n in dict.fromkeys(touched):
                muxes[n].flush()
        wall = time.perf_counter() - t0
        return (int(GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0),
                wall)

    def p99_ms(sinks):
        lats = sorted(x for sink in sinks for x in sink)
        if not lats:
            return None
        return round(lats[min(len(lats) - 1, int(0.99 * len(lats)))] * 1e3, 3)

    # warmup: walk both arms once on throwaway instances so every staged
    # variant (full-lane, offset-plane, per-session) compiles OUTSIDE the
    # measured pass — steady-state serving never pays an XLA compile
    drive_group(*build_group())
    drive_solo(*build_solo())

    group, gsids = build_group()
    # arm ONE shared plane across every fused lane: the row's per-stage
    # decomposition spans the whole tenant fleet, and the patch-equality
    # reads below double as the visibility watermark
    from peritext_tpu.obs.latency import LatencyPlane
    from peritext_tpu.obs.timeseries import TimeSeriesPlane

    plane = LatencyPlane().enable()
    # ...and ONE history plane: pump() feeds it an occupancy row per lane
    # per committed window — the raw material `propose(history=...)`
    # weights the cost model by (the closed planner loop)
    hist = TimeSeriesPlane(sample_every=1, min_frames=4)
    group.history = hist.enable()
    for n in names:
        group.muxes[n].latency_plane = plane
    fused_dispatches, fused_wall = drive_group(group, gsids)
    muxes, ssids = build_solo()
    solo_dispatches, solo_wall = drive_solo(muxes, ssids)

    # the correctness oracle: every tenant's patch stream byte-equal to
    # its standalone twin's
    for n in names:
        fused_patches = group.patches(n, gsids[n])
        solo_patches = muxes[n].patches(ssids[n])
        assert fused_patches == solo_patches, (
            f"fused/unfused patch divergence for {n}"
        )
    fusion = group.fusion_snapshot()
    amortization = (solo_dispatches / fused_dispatches
                    if fused_dispatches else 0.0)
    lat = plane.decomposition()
    assert lat["records"] > 0, (
        "armed latency plane sampled no fused drain batches"
    )
    assert lat["sum_consistent"], f"latency decomposition inconsistent: {lat}"
    assert all(v >= 0 for v in lat["stages_ms"].values()), (
        f"negative stage duration: {lat['stages_ms']}"
    )
    occ_rows = hist.occupancy_rows()
    assert occ_rows, (
        "armed history plane recorded no fused occupancy rows"
    )
    return {
        "metric": "serve_multitenant_dispatch_amortization",
        "value": round(amortization, 2),
        "unit": "x",
        "vs_baseline": round(solo_wall / fused_wall, 2) if fused_wall else None,
        "baseline_impl": "one standalone SessionMux drain per tenant",
        "tenants": tenants_n,
        "ops_per_doc": opd,
        "windows": len(plan),
        "fused_dispatches": fused_dispatches,
        "per_session_dispatches": solo_dispatches,
        "fused_wall_s": round(fused_wall, 4),
        "per_session_wall_s": round(solo_wall, 4),
        "fused_p99_apply_ms": p99_ms(
            [group.muxes[n].latency_sink for n in names]
        ),
        "per_session_p99_apply_ms": p99_ms(
            [muxes[n].latency_sink for n in names]
        ),
        "byte_equal": True,
        "latency": lat,
        "history_occupancy_rows": len(occ_rows),
        "history_occupancy": hist.snapshot()["occupancy"]["distribution"],
        "docs_per_dispatch": fusion["docs_per_dispatch"],
        "window_occupancy": fusion["window_occupancy"],
        "platform": jax.devices()[0].platform,
    }


def run_storm(args) -> dict:
    """Reconnect-storm row (ISSUE 7 / ROADMAP scenario item): a peer back
    from a long offline window drains a giant backlog through one gossip
    exchange WHILE the serving tier carries open-loop traffic.  Reports the
    backlog drain rate; the serving tier's p99 during the storm and the
    typed-verdict accounting ride along.  The same episode runs as a chaos
    schedule (testing/chaos.run_reconnect_storm asserts the oracles)."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.testing.chaos import run_reconnect_storm

    backlog = 500 if args.smoke else 4000
    report = run_reconnect_storm(
        args.seed + 3, backlog_ops=backlog, num_docs=args.docs,
        ops_per_doc=args.ops_per_doc,
        serve_rate_per_s=100.0 if args.smoke else 250.0,
        storm_duration_s=0.5 if args.smoke else 1.5,
    )
    return {
        "metric": "reconnect_storm_drain_ops_per_sec",
        "value": report.drain_ops_per_sec,
        "unit": "ops/s",
        "vs_baseline": None,
        "baseline_impl": "gossip backlog drain concurrent with open-loop serving",
        "backlog_ops": report.backlog_ops,
        "drain_seconds": report.drain_seconds,
        "serve_offered": report.offered,
        "serve_admitted": report.admitted,
        "serve_shed": report.shed,
        "serve_delayed": report.delayed,
        "serve_p99_apply_ms": report.p99_apply_ms,
        "serve_rounds": report.served_rounds,
        "queue_peak": report.queue_peak,
        "converged": report.converged,
        "platform": jax.devices()[0].platform,
    }


def run_longdoc(args) -> dict:
    """Long-tail workload family (ISSUE 8): one giant essay among a fleet
    of tweets — the distribution the padded (doc x op) layout is worst at,
    because every tweet pays the essay's stream width and slot bucket.

    The SAME workload merges through the padded DocBatch (the byte-equality
    oracle), the paged DocBatch (store/: page pool + per-doc page tables,
    size-bucketed groups) and the ragged DocBatch (ops/ragged.py: one
    program over the pool, per-doc counts as data — ISSUE 12); the row
    asserts byte equality, then reports every layout's wall clock and
    padded-op waste.  Headline = ragged throughput; ``vs_baseline`` =
    ragged/paged speedup (the bucket ladder this layout kills);
    ``vs_padded`` and the waste ratio (absolute padded ops burned,
    padded / paged; ragged burns ZERO) ride along.  ``--docs`` sizes the
    tweet fleet, ``--ops-per-doc`` the essay."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.api.batch import DocBatch
    from peritext_tpu.testing.fuzz import generate_workload

    d_small, big_ops, small_ops = args.docs, args.ops_per_doc, 8
    gen_start = time.perf_counter()
    workloads = generate_workload(seed=args.seed + 1, num_docs=d_small,
                                  ops_per_doc=small_ops)
    workloads += generate_workload(seed=args.seed + 90_001, num_docs=1,
                                   ops_per_doc=big_ops)
    gen_time = time.perf_counter() - gen_start
    total_ops = sum(
        len(ch.ops) for w in workloads for log in w.values() for ch in log
    )

    # slot capacity: power of two covering the essay (both layouts share
    # it — the padded layout must pay it for EVERY doc, which is the row's
    # whole point; paged pays it only in the essay's page table).  Rounded
    # to a page multiple so an odd --slots can't pass the padded half and
    # then crash the paged half's alignment check.
    from peritext_tpu.store import DEFAULT_PAGE_SIZE

    slots = args.slots or 256
    while slots < big_ops:
        slots *= 2
    slots = -(-slots // DEFAULT_PAGE_SIZE) * DEFAULT_PAGE_SIZE
    marks = args.marks or max(64, big_ops // 4)

    def measure(layout):
        batch = DocBatch(slot_capacity=slots, mark_capacity=marks,
                         layout=layout)
        report = batch.merge(workloads)  # warmup (compiles)
        t_best = None
        for _ in range(2):
            t0 = time.perf_counter()
            report = batch.merge(workloads)
            dt = time.perf_counter() - t0
            t_best = dt if t_best is None or dt < t_best else t_best
        return batch, report, t_best

    padded_batch, padded, wall_padded = measure("padded")
    paged_batch, paged, wall_paged = measure("paged")
    ragged_batch, ragged, wall_ragged = measure("ragged")
    for name, rep in (("paged", paged), ("ragged", ragged)):
        assert padded.spans == rep.spans, f"{name} layout diverged from padded"
        assert padded.roots == rep.roots, f"{name} roots diverged from padded"
        assert padded.fallback_docs == rep.fallback_docs

    # padded-op waste: absolute padded stream ops burned per layout (the
    # devprof occupancy quantity, derivable here from padding_efficiency)
    def wasted(report):
        eff = report.stats.padding_efficiency
        real = report.stats.device_ops + report.stats.fallback_ops
        capacity = real / eff if eff else 0.0
        return capacity - real, capacity

    waste_padded, cap_padded = wasted(padded)
    waste_paged, cap_paged = wasted(paged)
    waste_ragged, cap_ragged = wasted(ragged)
    pool_paged = paged_batch.last_store.pool_stats()
    pool = ragged_batch.last_store.pool_stats()
    value = total_ops / wall_ragged
    return {
        "metric": "longdoc_ragged_ops_per_sec",
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(wall_paged / wall_ragged, 2),
        "baseline_impl": "same long-tail workload through the paged "
                         "(pow-2 bucketed) layout",
        "vs_padded": round(wall_padded / wall_ragged, 2),
        "docs": d_small + 1,
        "small_doc_ops": small_ops,
        "big_doc_ops": big_ops,
        "total_ops": total_ops,
        "slot_capacity": slots,
        "byte_equal": True,
        "padded_ops_per_sec": round(total_ops / wall_padded, 1),
        "paged_ops_per_sec": round(total_ops / wall_paged, 1),
        "wall_padded_s": round(wall_padded, 3),
        "wall_paged_s": round(wall_paged, 3),
        "wall_ragged_s": round(wall_ragged, 3),
        "stream_capacity_padded": round(cap_padded),
        "stream_capacity_paged": round(cap_paged),
        "stream_capacity_ragged": round(cap_ragged),
        "padded_ops_wasted": round(waste_padded),
        "paged_ops_wasted": round(waste_paged),
        "ragged_ops_wasted": round(waste_ragged),
        "waste_ratio": round(waste_padded / waste_paged, 2) if waste_paged else None,
        "state_slots_padded": (d_small + 1) * slots,
        "state_slots_paged": pool_paged["pages_in_use"] * pool_paged["page_size"],
        "state_slots_ragged": pool["pages_in_use"] * pool["page_size"],
        "page_pool": pool,
        "workload_gen_seconds": round(gen_time, 1),
        "platform": jax.devices()[0].platform,
    }


def run_markheavy(args) -> dict:
    """Mark-heavy editorial-pass row (ISSUE 10 / ROADMAP scenario
    diversity): the span-overlap-explosion workload family — mostly long
    overlapping addMark/removeMark spans over a thin insert substrate —
    streamed through a session with the byte-equality oracle ATTACHED
    (device spans must equal the scalar oracle's, in-row).  Reports
    streaming throughput on the mark-heavy mix plus the mark/op ratio; the
    same family runs as a chaos schedule
    (testing/chaos.run_markheavy_chaos)."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.fuzz import (
        _campaign_session, generate_markheavy_workload,
    )

    d, opd = args.docs, args.ops_per_doc
    gen_start = time.perf_counter()
    workloads = generate_markheavy_workload(
        seed=args.seed + 17, num_docs=d, ops_per_doc=opd,
    )
    gen_time = time.perf_counter() - gen_start
    total_ops = 0
    mark_ops = 0
    for w in workloads:
        for log in w.values():
            for ch in log:
                for op in ch.ops:
                    total_ops += 1
                    if op.action in ("addMark", "removeMark"):
                        mark_ops += 1
    plans = []
    for w in workloads:
        changes = [ch for log in sorted(w) for ch in w[log]]
        plans.append([
            encode_frame(changes[i:i + 8])
            for i in range(0, len(changes), 8)
        ])

    def feed():
        session = _campaign_session(d, opd)
        for doc, frames in enumerate(plans):
            for f in frames:
                session.ingest_frame(doc, f)
        while session.drain() > 0:
            pass
        session.digest()
        return session

    feed()  # warmup (compiles)
    t_best = None
    for _ in range(2):
        t0 = time.perf_counter()
        session = feed()
        dt = time.perf_counter() - t0
        t_best = dt if t_best is None or dt < t_best else t_best

    # the byte-equality oracle, in-row: spans vs the scalar reference
    oracle = [_oracle_doc(w).get_text_with_formatting(["text"])
              for w in workloads]
    got = session.read_all()
    for doc in range(d):
        assert got[doc] == oracle[doc], (
            f"markheavy doc {doc}: device spans diverge from the scalar "
            "oracle"
        )
    value = total_ops / t_best
    return {
        "metric": "markheavy_ops_per_sec",
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "baseline_impl": "scalar-oracle byte equality asserted in-row",
        "docs": d,
        "ops_per_doc": opd,
        "total_ops": total_ops,
        "mark_ops": mark_ops,
        "mark_fraction": round(mark_ops / max(1, total_ops), 3),
        "byte_equal": True,
        "wall_seconds": round(t_best, 3),
        "fallback_docs": sum(1 for s in session.docs if s.fallback),
        "workload_gen_seconds": round(gen_time, 1),
        "platform": jax.devices()[0].platform,
    }


def run_fleet_serve(args) -> dict:
    """Fleet-serve row (ISSUE 10 tentpole evidence): the host-kill failover
    episode as a measurement — a ≥3-host FleetFrontend carries round-robin
    traffic, one serving host is killed mid-traffic, the lease detects it,
    failover re-homes the docs from checkpoint + journal, and client
    retries drain.  All of run_host_kill_failover's oracles (typed
    verdicts only, acked-op survival, post-heal fleet-wide byte equality)
    are ASSERTED in-row; the reported value is fleet frames applied per
    second over the whole episode, with the detection/failover evidence
    riding along."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.testing.chaos import run_host_kill_failover

    report = run_host_kill_failover(
        args.seed + 29,
        hosts=3,
        num_docs=args.docs,
        ops_per_doc=args.ops_per_doc,
        transport=not args.smoke,
    )
    value = report.applied_frames / max(report.traffic_seconds, 1e-9)
    return {
        "metric": "fleet_serve_applied_frames_per_sec",
        "value": round(value, 1),
        "unit": "frames/s",
        "vs_baseline": None,
        "baseline_impl": "host-kill failover episode, all oracles asserted",
        "hosts": report.hosts,
        "docs": report.num_docs,
        "ops_per_doc": args.ops_per_doc,
        "victim": report.victim,
        "victim_docs": report.victim_docs,
        "detection_rounds": report.detection_rounds,
        "failover_docs": report.failover_docs,
        "offered": report.offered,
        "admitted": report.admitted,
        "delayed": report.delayed,
        "shed": report.shed,
        "acked_survived": report.acked_survived,
        "converged": report.converged,
        "transport": "tcp" if not args.smoke else "in-process",
        "episode_seconds": round(report.traffic_seconds, 3),
        "platform": jax.devices()[0].platform,
    }


def run_sweep(args) -> dict:
    """Full-corpus sweep row (BASELINE config 5b, VERDICT r3 task 5): build
    an N-doc converged session on carried device state (the scale demo's
    shape: one 3-replica session streamed to every doc as wire frames over 2
    arrival rounds), then MEASURE the full read_all / read_patches_all
    sweeps and the full-state digest — the numbers round 3 projected from
    2,048-doc memoization measurements instead of timing."""
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    d = args.docs
    w = generate_workload(seed=args.seed, num_docs=1, ops_per_doc=args.ops_per_doc)[0]
    changes = [ch for log in w.values() for ch in log]
    half = len(changes) // 2
    frames = [encode_frame(changes[:half]), encode_frame(changes[half:])]
    expected = _oracle_doc(w).get_text_with_formatting(["text"])
    total_ops = sum(len(c.ops) for c in changes) * d

    sess = StreamingMerge(
        num_docs=d, actors=("doc1", "doc2", "doc3"),
        slot_capacity=512, mark_capacity=160, tomb_capacity=192,
        round_insert_capacity=192, round_delete_capacity=96,
        round_mark_capacity=96,
        layout=args.layout,
    )
    t0 = time.perf_counter()
    for frame in frames:
        sess.ingest_frames((doc, frame) for doc in range(d))
        sess.drain()
    build_seconds = time.perf_counter() - t0

    for doc in (0, d // 2, d - 1):
        assert sess.read(doc) == expected, f"doc {doc} diverged"
    assert not any(s.fallback for s in sess.docs), "docs demoted to scalar replay"
    assert sess.overflow_count() == 0

    t0 = time.perf_counter()
    digest = sess.digest()
    digest_seconds = time.perf_counter() - t0

    t0 = time.perf_counter()
    all_spans = sess.read_all()
    read_seconds = time.perf_counter() - t0
    assert all(s == expected for s in all_spans), "full-sweep read diverged"
    t0 = time.perf_counter()
    n_patches = sum(len(p) for p in sess.read_patches_all())
    patches_seconds = time.perf_counter() - t0

    sweep = read_seconds + patches_seconds
    return {
        "metric": "full_sweep_docs_per_sec",
        "value": round(d / sweep, 1),
        "unit": "docs/s",
        "vs_baseline": None,
        "layout": args.layout,
        "docs": d,
        "ops_per_doc_session": sum(len(c.ops) for c in changes),
        "total_ops": total_ops,
        "build_seconds": round(build_seconds, 1),
        "build_ops_per_sec": round(total_ops / build_seconds, 1),
        "digest": f"{digest:#010x}",
        "digest_seconds": round(digest_seconds, 2),
        "read_all_seconds": round(read_seconds, 2),
        "read_patches_all_seconds": round(patches_seconds, 2),
        "sweep_seconds": round(sweep, 2),
        "n_patches": n_patches,
        "platform": jax.devices()[0].platform,
    }


def run_mesh(args) -> dict:
    """Mesh-sharded host row (ISSUE 14): the doc-axis ``shard_map`` fused
    drain swept over shard counts, byte equality vs the single-device
    fused path asserted in-row.

    Each rung builds a fresh paged-layout session over a 1/2/4/8-device
    mesh (virtual CPU devices on a single-chip host — the flag must land
    before the backend initializes, hence the env fixup below), replays
    the same fuzz workload through the fused drain, asserts digest +
    ``read_all`` equality against the meshless fused reference, and times
    steady-state replay sessions (the warmup session pays the rung's
    compiles; the jit + mesh_fn caches carry them across sessions).  A
    drain batch is ONE staged program for the whole mesh, so the rung's
    fused-dispatch count rides along with ``speedup_vs_1shard``."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from peritext_tpu.obs import GLOBAL_COUNTERS
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    d = args.docs
    opd = args.ops_per_doc
    workloads = generate_workload(seed=args.seed + 19, num_docs=d,
                                  ops_per_doc=opd)
    changes = [[ch for log in w.values() for ch in log] for w in workloads]
    total_ops = sum(len(c.ops) for log in changes for c in log)

    def replay(mesh):
        sess = StreamingMerge(
            num_docs=d, actors=("doc1", "doc2", "doc3"),
            layout="paged", mesh=mesh,
            slot_capacity=max(256, 4 * opd), mark_capacity=max(128, opd),
            tomb_capacity=max(128, opd),
        )
        for doc, log in enumerate(changes):
            sess.ingest(doc, log)
        sess.drain()
        return sess

    ref = replay(None)
    ref_digest = ref.digest()
    ref_spans = ref.read_all()

    devices = jax.devices()
    shard_counts = [n for n in (1, 2, 4, 8)
                    if n <= len(devices) and d % n == 0]
    iters = max(2, args.iters // 2)
    rungs = []
    base_ops_per_sec = None
    for n in shard_counts:
        mesh = Mesh(np.asarray(devices[:n]), ("docs",))
        # warmup replay: pays the rung's compiles AND is the oracle check
        sess = replay(mesh)
        assert sess.digest() == ref_digest, f"{n}-shard digest diverged"
        assert sess.read_all() == ref_spans, f"{n}-shard read_all diverged"
        d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
        t0 = time.perf_counter()
        for _ in range(iters):
            sess = replay(mesh)
        elapsed = max(time.perf_counter() - t0, 1e-9)
        ops_per_sec = total_ops * iters / elapsed
        if base_ops_per_sec is None:
            base_ops_per_sec = ops_per_sec
        stats = sess._mesh_stats()
        rungs.append({
            "shards": n,
            "ops_per_sec": round(ops_per_sec, 1),
            "seconds": round(elapsed, 3),
            "sessions": iters,
            "fused_dispatches": int(
                GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0
            ),
            "speedup_vs_1shard": round(ops_per_sec / base_ops_per_sec, 3),
            "imbalance_ratio": stats.get("imbalance_ratio"),
            "ici_page_moves": stats.get("ici_page_moves"),
            "equality": "byte-identical",
        })
    widest = rungs[-1]
    return {
        "metric": "mesh_sustained_ops_per_sec",
        "value": widest["ops_per_sec"],
        "unit": "ops/s",
        "vs_baseline": None,
        "baseline_impl": "single-device fused drain, byte equality in-row",
        "layout": "paged",
        "docs": d,
        "ops_per_doc": opd,
        "shards": widest["shards"],
        "speedup_vs_1shard": widest["speedup_vs_1shard"],
        "rungs": rungs,
        "platform": jax.devices()[0].platform,
    }


def ladder_rows(platform: str):
    """The evidence-ladder row specs: (name, BASELINE config tag, worker
    args, platform, timeout).  Ordered so the highest-value rows land first
    if the global deadline cuts the run short.  On a dead tunnel the SAME
    ladder runs with platform='cpu' — full configs, never the smoke config
    alone (VERDICT r3 task 1)."""
    t = ROW_TIMEOUT
    return [
        ("baselines",    "1",  ["--mode", "baselines"], "cpu", t),
        ("batch_8k",     "4",  ["--mode", "batch"], platform, t),
        # the ragged twin (ISSUE 12): same synth batch, one program over
        # the page pool, padded byte-equality asserted in-row
        ("batch_8k_ragged", "4r", ["--mode", "batch", "--layout", "ragged"],
         platform, t),
        ("streaming",    "5",  ["--mode", "streaming"], platform, t),
        ("streaming_fused", "5f", ["--mode", "streaming-fused"], platform, t),
        ("wire",         "-",  ["--mode", "wire"], "cpu", t),
        ("fleet_heal",   "-",  ["--mode", "fleet"], "cpu", t),
        ("engine",       "5e", ["--mode", "engine"], platform, t),
        ("batch_1k",     "3",  ["--mode", "batch", "--docs", "1024"], platform, t),
        ("batch_128_cpu", "2", ["--mode", "batch", "--docs", "128"], "cpu", t),
        ("serve_sustained", "-", ["--mode", "serve"], platform, t),
        # the multi-tenant fused-dispatch row (ISSUE 13): N small tenants
        # on one lane vs per-session drains, byte equality asserted in-row
        ("serve_multitenant", "-", ["--mode", "serve-fused"], platform, t),
        # the mesh-sharded host row (ISSUE 14): shard_map fused drain over
        # 1/2/4/8 virtual devices, single-device byte equality in-row
        ("serve_mesh_sustained", "-", ["--mode", "mesh"], "cpu", t),
        ("reconnect_storm", "-", ["--mode", "storm"], platform, t),
        ("batch_longdoc", "4b", ["--mode", "longdoc"], platform, t),
        ("markheavy",    "-",  ["--mode", "markheavy"], platform, t),
        ("fleet_serve",  "-",  ["--mode", "fleet-serve"], "cpu", t),
        ("sweep_100k",   "5b", ["--mode", "sweep"], platform, max(t, 1800.0)),
        # the paged-vs-padded sweep comparison: same 100K-doc corpus, paged
        # resident storage — gate history is per row name, so regressions
        # in EITHER layout's sweep show up independently
        ("sweep_paged",  "5b", ["--mode", "sweep", "--layout", "paged"],
         platform, max(t, 1800.0)),
    ]


def orchestrate_ladder(args) -> int:
    """The no-args default: probe once, then run EVERY evidence row as its
    own bounded worker and print one JSON line whose ``rows`` array carries
    the whole ladder (VERDICT r3 task 1).  A row failure/timeout records a
    structured entry and — if it happened on the probed TPU — flips the
    remaining ladder to CPU, re-running the failed row there; the headline
    fields mirror the best batch row so the driver contract (one line,
    metric/value/vs_baseline) is unchanged."""
    t_start = time.perf_counter()
    extras = {}
    if getattr(args, "profile", None) or getattr(args, "object_ingest", False):
        print("bench: --profile/--object-ingest are not supported by the "
              "ladder and will be ignored (use --mode batch/streaming)",
              file=sys.stderr)
    if args.platform:
        platform = args.platform
    else:
        t0 = time.perf_counter()
        platform, probe_tail = probe_device()
        extras["probe_seconds"] = round(time.perf_counter() - t0, 1)
        if platform is None:
            extras["tpu_unavailable"] = True
            extras["tpu_error"] = probe_tail
            platform = "cpu"
        elif platform == "cpu":
            extras["tpu_unavailable"] = True
            extras["tpu_error"] = "default jax backend is cpu (no TPU plugin attached)"

    only = os.environ.get("PT_BENCH_LADDER_ROWS")
    specs = ladder_rows(platform)
    if only:
        wanted = {w.strip() for w in only.split(",")}
        specs = [s for s in specs if s[0] in wanted]

    rows = []
    baselines_blob = None
    queue = list(specs)
    while queue:
        name, config, rargs, plat, timeout = queue.pop(0)
        if plat != "cpu" and platform == "cpu":
            plat = "cpu"  # ladder flipped to CPU after a TPU row died
        left = LADDER_DEADLINE - (time.perf_counter() - t_start)
        if left < 30:
            rows.append({"row": name, "config": config, "skipped": "ladder deadline"})
            continue
        worker_args = list(rargs)
        if args.smoke:
            worker_args.append("--smoke")
        if args.devprof:
            worker_args.append("--devprof")
        if args.iters != 10:  # explicit --mode ladder may shape the workers
            worker_args += ["--iters", str(args.iters)]
        if args.seed:
            worker_args += ["--seed", str(args.seed)]
        if plat == "cpu" or args.platform:
            worker_args += ["--platform", plat]
        env = dict(os.environ)
        if baselines_blob:
            env["PT_BENCH_BASELINES"] = baselines_blob
        rc, out, err = _run_bounded(
            _worker_argv(worker_args), min(timeout, left), env=env
        )
        result = _parse_json_tail(out)
        if rc == 0 and result is not None:
            result["row"] = name
            result["config"] = config
            rows.append(result)
            if name == "baselines":
                baselines_blob = json.dumps(result)
            continue
        status = "timed out" if rc is None else f"rc={rc}"
        tail = (err or out).strip()[-800:]
        print(f"bench: ladder row {name} on {plat} {status}: {tail}",
              file=sys.stderr)
        rows.append({"row": name, "config": config, "platform_attempted": plat,
                     "failed": True, "error": f"{status}: {tail}"})
        if plat != "cpu":
            # TPU passed the probe but a row died mid-ladder: flip the rest
            # (and this row) to CPU so the record still carries the ladder.
            extras["tpu_unavailable"] = True
            extras["tpu_error"] = f"ladder row {name} on {plat} {status}"
            platform = "cpu"
            queue.insert(0, (name, config, rargs, "cpu", timeout))

    extras["ladder_seconds"] = round(time.perf_counter() - t_start, 1)
    headline = None
    for want in ("batch_8k", "batch_1k", "batch_128_cpu", "streaming"):
        headline = next(
            (r for r in rows if r.get("row") == want and not r.get("failed")
             and not r.get("skipped")), None)
        if headline:
            break
    # a row subset (PT_BENCH_LADDER_ROWS) may not include a batch/streaming
    # row at all: all-green rows are still a success, not a failure record
    all_ok = bool(rows) and all(
        not r.get("failed") and not r.get("skipped") for r in rows
    )
    record = {
        "metric": headline.get("metric") if headline else "crdt_ops_per_sec_per_chip",
        "value": headline.get("value") if headline else None,
        "unit": "ops/s",
        "vs_baseline": headline.get("vs_baseline") if headline else None,
        "headline_row": headline.get("row") if headline else None,
        **({} if headline or all_ok else {"failed": True}),
        "rows": rows,
        **extras,
    }
    # Full record: sidecar file (the judge's evidence) + an early stdout line
    # (so a human log still carries everything).  The LAST line is the
    # compact summary the driver parses — budget enforced by compact_record
    # and pinned by tests/test_bench_harness.py.
    try:
        with open(SIDECAR, "w") as fh:
            json.dump(record, fh, indent=1)
        record["sidecar"] = os.path.basename(SIDECAR)
    except OSError as exc:  # unwritable sidecar dir must not cost the line
        print(f"bench: sidecar write failed: {exc}", file=sys.stderr)
    if args.ledger:
        # perf-ledger emission: ladder rows (devprof snapshots lifted out of
        # the rows and keyed per row) appended as ONE record for the
        # regression gate (python -m peritext_tpu.obs perf --gate)
        devprof_map = {}
        ledger_rows = []
        for r in rows:
            r = dict(r)
            snap = r.pop("devprof", None)
            if snap is not None:
                devprof_map[r.get("row")] = snap
            ledger_rows.append(r)
        _append_ledger(
            args.ledger, ledger_rows,
            config="ladder" + ("-smoke" if args.smoke else ""),
            platform=platform, devprof=devprof_map or None,
        )
    print(json.dumps(record))
    print(json.dumps(compact_record(record)))
    return 0 if headline or all_ok else 1


def compact_record(record, budget=None):
    """Shrink a full ladder record to the driver-parsed summary: headline
    fields plus per-row ``{row, value, unit, platform, config, vs_baseline}``
    (and failure markers), guaranteed to serialize within ``budget`` bytes
    (VERDICT r4 task 1).  Degrades by dropping optional per-row fields, then
    trailing rows, never the headline."""
    budget = FINAL_LINE_BUDGET if budget is None else budget
    head = {k: record.get(k) for k in
            ("metric", "value", "unit", "vs_baseline", "headline_row")}
    if record.get("failed"):
        head["failed"] = True
    for k in ("sidecar", "tpu_unavailable", "probe_seconds", "ladder_seconds"):
        if k in record:
            head[k] = record[k]
    if "tpu_error" in record:
        head["tpu_error"] = str(record["tpu_error"])[:160]

    def row_of(r, keys):
        out = {"row": r.get("row")}
        for k in keys:
            if r.get(k) is not None:
                out[k] = r[k]
        if r.get("failed"):
            out["failed"] = True
        if r.get("skipped"):
            out["skipped"] = True
        return out

    tiers = (("value", "unit", "platform", "config", "vs_baseline"),
             ("value", "unit", "platform"),
             ("value",))
    # degrade fields first (all rows kept), truncate rows only when even
    # the slimmest field tier overflows
    for keys in tiers:
        out = dict(head, rows=[row_of(r, keys) for r in record.get("rows", [])])
        if len(json.dumps(out)) <= budget:
            return out
    while out["rows"]:
        out["rows"] = out["rows"][:-1]
        out["rows_truncated"] = True
        if len(json.dumps(out)) <= budget:
            return out
    head["rows"] = []
    if len(json.dumps(head)) > budget and "tpu_error" in head:
        head["tpu_error"] = head["tpu_error"][:40]
        if len(json.dumps(head)) > budget:
            del head["tpu_error"]
    return head


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--smoke", action="store_true", help="small fast config")
    parser.add_argument(
        "--mode",
        choices=("batch", "streaming", "streaming-fused", "engine", "wire",
                 "sweep", "baselines", "fleet", "serve", "serve-fused",
                 "mesh", "storm", "longdoc", "markheavy", "fleet-serve",
                 "ladder"),
        default=None,
        help="batch = one-shot converge (configs 2-4); streaming = config 5 "
             "end-to-end; engine = device-only streaming replay (the engine "
             "limit, decoupled from host parse/link); wire = codec bytes/op; "
             "sweep = config-5b full-corpus read sweep; baselines = scalar "
             "baselines only; fleet = partition-heal time-to-convergence "
             "(ISSUE 4); serve = sustained open-loop serving ladder (docs/s "
             "at a p99 apply-latency SLO, ISSUE 7); serve-fused = N small "
             "tenants fused onto one device lane vs per-session dispatch "
             "(dispatch amortization + byte equality, ISSUE 13); "
             "mesh = doc-axis-sharded shard_map fused drain swept over "
             "shard counts (single-device byte equality in-row, ISSUE 14); "
             "storm = reconnect-storm "
             "backlog drain under serving load; longdoc = long-tail "
             "paged-vs-padded comparison (one essay among a tweet fleet, "
             "ISSUE 8); markheavy = mark-heavy editorial pass (span-overlap "
             "explosion, scalar-oracle byte equality in-row, ISSUE 10); "
             "fleet-serve = host-kill failover episode as a measurement "
             "(ISSUE 10); ladder = every row as "
             "bounded sub-workers (the default when invoked with no mode "
             "and no --smoke)",
    )
    parser.add_argument("--rounds", type=int, default=4, help="streaming arrival rounds")
    parser.add_argument(
        "--object-ingest", action="store_true",
        help="streaming: force the Python object ingest path (default: wire frames)",
    )
    parser.add_argument("--docs", type=int, default=None)
    parser.add_argument("--ops-per-doc", type=int, default=None)
    parser.add_argument("--slots", type=int, default=None)
    parser.add_argument("--marks", type=int, default=None)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--platform", default=None, help="force a jax platform (e.g. cpu)"
    )
    parser.add_argument(
        "--layout", choices=("padded", "paged", "ragged"), default="padded",
        help="resident-state storage layout for the sweep row and (ragged "
             "only) the batch row's one-program-over-the-pool variant; the "
             "longdoc row always measures all three layouts itself",
    )
    parser.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture a jax.profiler trace of the steady-state loop into DIR",
    )
    parser.add_argument(
        "--trace-out", default=None, metavar="PATH", dest="trace_out",
        help="write the streaming pipeline spans as Perfetto/Chrome "
             "trace-event JSON to PATH (streaming mode)",
    )
    parser.add_argument(
        "--serve-slo-ms", type=float, default=250.0, dest="serve_slo_ms",
        metavar="MS",
        help="serve mode: the p99 apply-latency SLO the open-loop ladder "
             "sweeps against (default 250 ms)",
    )
    parser.add_argument(
        "--devprof", action="store_true",
        help="enable device-cost profiling (obs/devprof.py: XLA cost/memory "
             "introspection + bucket occupancy) for the measured rows; the "
             "snapshot lands in the row JSON and the perf ledger",
    )
    parser.add_argument(
        "--ledger", default=None, metavar="PATH",
        help="append the run's rows (+ devprof snapshots) to the JSONL perf "
             "ledger at PATH; gate with `python -m peritext_tpu.obs perf`",
    )
    parser.add_argument(
        "--_worker", action="store_true", dest="worker", help=argparse.SUPPRESS
    )
    args = parser.parse_args()

    if args.trace_out and args.mode not in ("streaming",):
        # only the streaming runner consumes it; anything else would both
        # skip the default ladder AND silently write no trace
        parser.error("--trace-out requires --mode streaming")
    layout_modes = {"paged": ("sweep",), "ragged": ("sweep", "batch")}
    if args.layout != "padded" and args.mode not in layout_modes[args.layout]:
        # only these runners consume it (longdoc measures every layout
        # itself); anything else would silently measure the padded layout
        parser.error(
            f"--layout {args.layout} requires --mode "
            + "/".join(layout_modes[args.layout])
        )

    explicit_sizing = (
        any(v is not None for v in (args.docs, args.ops_per_doc, args.slots,
                                    args.marks, args.profile, args.trace_out))
        or args.iters != 10 or args.seed != 0 or args.rounds != 4
        or args.object_ingest
    )
    if not args.worker:
        if args.mode is None and not args.smoke and not explicit_sizing:
            # the driver's plain `python bench.py`: the full evidence ladder
            # (explicit sizing flags mean a hand-run single measurement —
            # ladder_rows would silently drop them, so classic batch instead)
            sys.exit(orchestrate_ladder(args))
        args.mode = args.mode or "batch"
        # argv minus the program name IS the passthrough (worker re-parses it);
        # --platform is re-added per attempt by the orchestrator.
        argv = sys.argv[1:]
        passthrough = [a for i, a in enumerate(argv)
                       if a != "--platform"
                       and not a.startswith("--platform=")
                       and not (i > 0 and argv[i - 1] == "--platform")]
        if args.mode == "ladder":  # --smoke ladder: shrunk rows, same shape
            sys.exit(orchestrate_ladder(args))
        sys.exit(orchestrate(args, passthrough))

    args.mode = args.mode or "batch"
    if args.mode == "sweep":
        defaults = (2000, 220, 0, 0) if args.smoke else (100_000, 220, 0, 0)
        args.seed = args.seed or 200
    elif args.mode in ("wire", "fleet"):
        defaults = (64, 192, 0, 0) if args.smoke else (512, 192, 0, 0)
    elif args.mode == "serve":
        defaults = (16, 48, 0, 0) if args.smoke else (64, 96, 0, 0)
    elif args.mode == "serve-fused":
        # --docs = the tenant count (one doc slot per small tenant)
        defaults = (16, 48, 0, 0) if args.smoke else (32, 96, 0, 0)
    elif args.mode == "mesh":
        # docs stay divisible by every swept shard count (1/2/4/8)
        defaults = (16, 48, 0, 0) if args.smoke else (64, 96, 0, 0)
    elif args.mode == "storm":
        defaults = (4, 30, 0, 0) if args.smoke else (8, 64, 0, 0)
    elif args.mode == "longdoc":
        # --docs = the tweet fleet, --ops-per-doc = the essay
        defaults = (64, 512, 0, 0) if args.smoke else (1024, 8192, 0, 0)
    elif args.mode == "markheavy":
        defaults = (16, 64, 0, 0) if args.smoke else (256, 192, 0, 0)
    elif args.mode == "fleet-serve":
        defaults = (4, 16, 0, 0) if args.smoke else (8, 48, 0, 0)
    elif args.mode in ("streaming", "streaming-fused", "engine"):
        defaults = (64, 96, 256, 64) if args.smoke else (2048, 192, 384, 96)
    else:
        defaults = (64, 128, 192, 64) if args.smoke else (8192, 256, 384, 96)
    args.docs = args.docs or defaults[0]
    args.ops_per_doc = args.ops_per_doc or defaults[1]
    args.slots = args.slots or defaults[2]
    args.marks = args.marks or defaults[3]

    runners = {"streaming": run_streaming,
               "streaming-fused": run_streaming_fused,
               "engine": run_engine, "batch": run,
               "wire": run_wire, "sweep": run_sweep, "baselines": run_baselines,
               "fleet": run_fleet_heal, "serve": run_serve,
               "serve-fused": run_serve_fused, "mesh": run_mesh,
               "storm": run_storm,
               "longdoc": run_longdoc, "markheavy": run_markheavy,
               "fleet-serve": run_fleet_serve}
    if args.devprof:
        # arm the process profiler before any jit dispatches; cost capture
        # on — the worker is a bounded measurement run, and the AOT
        # captures happen once per compiled shape
        from peritext_tpu.obs import GLOBAL_DEVPROF

        GLOBAL_DEVPROF.enable(capture_costs=True)
    result = runners[args.mode](args)
    if args.devprof:
        result["devprof"] = GLOBAL_DEVPROF.snapshot()
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
