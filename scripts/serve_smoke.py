#!/usr/bin/env python
"""serve smoke: the serving tier's CI contract (and ``make serve-smoke``).

Runs the serving tier end to end on CPU and asserts its three promises:

* **typed verdicts under overload** — a burst far beyond the bounded
  ingest queue produces ``delay``/``shed`` verdicts from the typed
  vocabulary, the accounting identity holds (zero silent drops), and the
  queue depth never exceeds its bound;
* **byte equality** — after the overload clears and shed frames are
  redelivered, the mux's device state equals a fault-free reference
  session bit-for-bit;
* **observable** — ``/serve.json`` scrapes render through
  ``python -m peritext_tpu.obs serve``, which exits 1 on the overloaded
  snapshot and 0 on the drained one (the health-check contract).

A short open-loop rung also runs so the artifact carries a latency
readout.  Artifacts (``serve-report.json``, the two ``/serve.json``
snapshots) are written for upload.  Exit nonzero on any violation — a
serving-tier regression fails CI like a correctness one.
"""

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=3)
    parser.add_argument("--out", default="serve-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    from peritext_tpu.obs.__main__ import main as obs_main
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.serve import (
        AdmissionController,
        SHED_REASONS,
        SessionMux,
        build_arrivals,
        run_open_loop,
    )
    from peritext_tpu.testing.chaos import _serve_session
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    num_docs, ops_per_doc, max_depth = 6, 40, 24
    workloads = generate_workload(args.seed, num_docs=num_docs,
                                  ops_per_doc=ops_per_doc)
    plans = []
    for w in workloads:
        changes = [ch for log in w.values() for ch in log]
        plans.append([encode_frame(changes[i:i + 5])
                      for i in range(0, len(changes), 5)])

    mux = SessionMux(
        _serve_session(num_docs, ops_per_doc),
        admission=AdmissionController(max_depth=max_depth,
                                      session_quota=None),
        host="serve-smoke",
    )
    sids = []
    for d in range(num_docs):
        sid, verdict = mux.open_session(f"client{d}")
        assert verdict.admitted, verdict
        sids.append(sid)

    # -- overload burst: typed verdicts, bounded queue ----------------------
    admitted = [[] for _ in range(num_docs)]
    for k in range(max_depth * 6):
        doc = k % num_docs
        frame = plans[doc][(k // num_docs) % len(plans[doc])]
        verdict = mux.submit(sids[doc], frame)
        assert mux.admission.depth <= max_depth, "queue bound violated"
        if verdict.kind == "admit":
            admitted[doc].append(frame)
        elif verdict.kind == "shed":
            assert verdict.reason in SHED_REASONS, verdict
    stats = mux.admission.stats
    assert stats.submitted == stats.admitted + stats.delayed + stats.shed
    assert stats.shed > 0, "the overload burst must shed"
    # freeze the burst-phase verdict counts: `stats` is live and the
    # redelivery below keeps counting into it
    burst = stats.to_json()
    burst_peak = mux.admission.peak_depth
    overloaded_snap = out / "serve-overloaded.json"
    overloaded_snap.write_text(json.dumps(mux.snapshot(), indent=1))

    # the health-check contract: overloaded/shedding scrape exits 1
    rc = obs_main(["serve", str(overloaded_snap)])
    assert rc == 1, f"obs serve must flag the overloaded snapshot (rc={rc})"

    # -- drain + redeliver: byte equality -----------------------------------
    mux.flush()
    reference = _serve_session(num_docs, ops_per_doc)
    for doc, frames in enumerate(admitted):
        for f in frames:
            reference.ingest_frame(doc, f)
    reference.drain()
    assert mux.session.digest() == reference.digest(), (
        "admitted-set digest mismatch after the overload drained"
    )
    clean = _serve_session(num_docs, ops_per_doc)
    for doc, frames in enumerate(plans):
        for f in frames:
            clean.ingest_frame(doc, f)
    clean.drain()
    for doc, frames in enumerate(plans):
        for f in frames:
            while True:
                if mux.submit(sids[doc], f).kind == "admit":
                    break
                mux.flush()
    mux.flush()
    assert mux.session.digest() == clean.digest(), (
        "redelivered state must equal the fault-free session byte-for-bit"
    )

    # -- a short open-loop rung for the latency readout ---------------------
    lat_mux = SessionMux(
        _serve_session(num_docs, ops_per_doc),
        admission=AdmissionController(max_depth=256, session_quota=None),
        host="serve-smoke",
    )
    frames_by_session = {}
    for d in range(num_docs):
        sid, _ = lat_mux.open_session(f"open{d}")
        frames_by_session[sid] = plans[d]
    rung = run_open_loop(
        lat_mux, build_arrivals(frames_by_session, 120.0, 0.5),
        deadline_s=4.0,
    )
    assert rung.accounted()
    healthy_snap = out / "serve-healthy.json"
    healthy_snap.write_text(json.dumps(lat_mux.snapshot(), indent=1))
    rc = obs_main(["serve", str(healthy_snap)])
    assert rc == 0, f"obs serve must pass the healthy snapshot (rc={rc})"

    report = {
        "seed": args.seed,
        "overload": {**burst, "queue_peak": burst_peak,
                     "queue_max_depth": max_depth},
        "open_loop": rung.to_json(),
        "digest": f"{clean.digest():#010x}",
    }
    (out / "serve-report.json").write_text(json.dumps(report, indent=1))
    print(
        f"serve smoke: offered {burst['submitted']} under overload -> "
        f"{burst['admitted']} admitted / {burst['delayed']} delayed / "
        f"{burst['shed']} shed ({burst['shed_reasons']}), "
        f"queue peak {burst_peak}/{max_depth}; open loop "
        f"{rung.rate_per_s:.0f}/s p99 {rung.p99_apply_s * 1e3:.1f} ms; "
        f"byte-equal after redelivery"
    )
    print(f"serve smoke: artifacts in {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
