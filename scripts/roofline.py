"""Memory-roofline measurement for apply/resolve (VERDICT r4 task 4).

One run, one process, interleaved: (a) pure state-copy programs at three
doc counts calibrate achievable HBM bandwidth THROUGH THIS PLATFORM and
its per-dispatch floor; (b) the batch apply and resolve programs at the
batch_8k shape measure bytes-moved/op and achieved GB/s against that
calibration.  Emits the BASELINE.md table rows.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def state_bytes(st):
    return sum(int(np.prod(x.shape)) * x.dtype.itemsize for x in st)


def steady(fn, arg, reps=8, chain=True):
    def sync(o):
        np.asarray(o.num_slots if hasattr(o, "num_slots") else o.overflow)

    sync(fn(arg))
    t0 = time.perf_counter()
    o = arg
    for _ in range(reps):
        o = fn(o) if chain else fn(arg)
    sync(o)
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    from peritext_tpu.ops.kernel import apply_batch_jit
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.ops.resolve import resolve_jit
    from peritext_tpu.testing.synth import synth_streams, synth_total_ops

    print(f"device: {jax.devices()[0]}")

    # (a) copy calibration: how fast can ANY program move state bytes here?
    copy = jax.jit(lambda st: type(st)(*(x + 1 if x.dtype != jnp.bool_
                                         else x for x in st)))
    for d in (2048, 8192, 32768):
        st = jax.device_put(empty_docs(d, 384, 96, tomb_capacity=64))
        b = state_bytes(st)
        t = steady(copy, st)
        print(f"copy d={d:6d}: {b/1e6:7.1f} MB state, {t*1e3:7.2f} ms/call, "
              f"{2*b/t/1e9:6.1f} GB/s (r+w)")

    # (b) batch_8k apply + resolve (bench --mode batch shapes)
    d, k, s_cap, m = 8192, 256, 384, 96
    ki, kd = int(k * 0.7), int(k * 0.15)
    km = k - ki - kd
    streams = synth_streams(d, inserts_per_doc=ki, deletes_per_doc=kd,
                            marks_per_doc=km, seed=0)
    total_ops = synth_total_ops(streams)
    state0 = jax.device_put(empty_docs(d, s_cap, max(m, km),
                                       tomb_capacity=max(kd, 8)))
    ops_dev = jax.device_put(streams)
    sb = state_bytes(state0)
    stream_b = sum(int(np.prod(np.shape(x))) * 4 for x in jax.tree.leaves(streams))

    t = steady(lambda st: apply_batch_jit(st, ops_dev, insert_loop_slots=ki),
               state0)
    moved = 2 * sb + stream_b  # state r+w, streams r — one pass each
    print(f"apply batch_8k: {t*1e3:7.2f} ms, {total_ops/t/1e6:6.1f} M ops/s, "
          f"{moved/1e6:6.1f} MB min-moved, {moved/t/1e9:6.1f} GB/s achieved, "
          f"{moved/total_ops:5.1f} B/op")

    applied = apply_batch_jit(state0, ops_dev, insert_loop_slots=ki)
    np.asarray(applied.num_slots)
    tr = steady(lambda st: resolve_jit(st, 32), applied, chain=False)
    # resolve reads state, writes (D, S) visible/fmt planes ~ 3 planes
    rb = sb + 3 * d * s_cap * 4
    print(f"resolve:        {tr*1e3:7.2f} ms, {rb/1e6:6.1f} MB min-moved, "
          f"{rb/tr/1e9:6.1f} GB/s achieved, {rb/total_ops:5.1f} B/op")


if __name__ == "__main__":
    main()
