"""Per-dispatch cost of the round-apply program through the axon tunnel.

Times N back-to-back identical apply_batch_compact_jit dispatches (args
already device-resident, one sync at the end) and one tiny no-op program,
separating fixed per-launch latency from compute.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    docs, slots, marks = 2048, 384, 96
    from peritext_tpu.ops.kernel import apply_batch_compact_jit
    from peritext_tpu.ops.packed import empty_docs

    state = jax.device_put(empty_docs(docs, slots, marks, tomb_capacity=slots))

    ki, kd, km, kp = 64, 32, 32, 8
    n = np.full(docs, 4, np.int32)
    counts = tuple(jax.device_put(x) for x in
                   (n, np.zeros(docs, np.int32), np.zeros(docs, np.int32),
                    np.zeros(docs, np.int32)))
    tot = int(n.sum())
    ins = tuple(jax.device_put(np.zeros(tot, np.int32)) for _ in range(3))
    dels = jax.device_put(np.zeros(0, np.int32))
    from peritext_tpu.ops.encode import MARK_COLS
    from peritext_tpu.ops.packed import MAP_STREAM_COLS
    mk = {c: jax.device_put(np.zeros(0, np.int32)) for c in MARK_COLS}
    mp = {c: jax.device_put(np.zeros(0, np.int32)) for c in MAP_STREAM_COLS}

    def one(st):
        return apply_batch_compact_jit(st, counts, ins, dels, mk, mp,
                                       widths=(ki, kd, km, kp))

    st = one(state)
    jax.block_until_ready(st.char)

    for reps in (1, 4, 16, 64):
        t0 = time.perf_counter()
        st = state
        for _ in range(reps):
            st = one(st)
        jax.block_until_ready(st.char)
        dt = time.perf_counter() - t0
        print(f"chained x{reps}: {dt*1e3:8.1f} ms total, "
              f"{dt*1e3/reps:7.2f} ms/dispatch")

    tiny = jax.jit(lambda x: x + 1)
    x = jax.device_put(jnp.zeros(8, jnp.int32))
    jax.block_until_ready(tiny(x))
    for reps in (1, 64):
        t0 = time.perf_counter()
        y = x
        for _ in range(reps):
            y = tiny(y)
        jax.block_until_ready(y)
        dt = time.perf_counter() - t0
        print(f"tiny    x{reps}: {dt*1e3:8.1f} ms total, "
              f"{dt*1e3/reps:7.2f} ms/dispatch")


if __name__ == "__main__":
    main()
