#!/usr/bin/env python
"""Chaos soak: N seeded campaigns of the composed fault harness.

Each seed runs ``peritext_tpu.testing.chaos.run_chaos`` — delivery faults +
payload corruption + peer stalls + injected device-round failures +
crash-restore, all against the byte-equality convergence oracle.  Any oracle
violation or unhandled exception fails the soak with the seed in the error.

Usage::

    python scripts/chaos_soak.py --seeds 20            # the `make chaos` run
    python scripts/chaos_soak.py --seeds 200 --docs 8  # a long soak
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="Composed-fault chaos soak")
    parser.add_argument("--seeds", type=int, default=20,
                        help="number of seeded campaigns")
    parser.add_argument("--seed0", type=int, default=0,
                        help="first seed (campaigns run seed0..seed0+seeds-1)")
    parser.add_argument("--docs", type=int, default=6)
    parser.add_argument("--ops", type=int, default=40)
    parser.add_argument("--no-transport", action="store_true",
                        help="skip the peer-stall transport episode")
    parser.add_argument("--no-crash", action="store_true",
                        help="skip the crash-restore episode")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON line per campaign")
    args = parser.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from peritext_tpu.observability import GLOBAL_COUNTERS, health_snapshot
    from peritext_tpu.testing.chaos import run_chaos

    t0 = time.time()
    failures = 0
    for seed in range(args.seed0, args.seed0 + args.seeds):
        try:
            report = run_chaos(
                seed, num_docs=args.docs, ops_per_doc=args.ops,
                transport=not args.no_transport, crash=not args.no_crash,
            )
        except Exception as exc:  # noqa: BLE001 - soak reports, then fails
            failures += 1
            print(f"seed {seed:4d}: FAILED — {exc}", file=sys.stderr)
            continue
        if args.json:
            print(json.dumps(report.to_json()))
        else:
            print(
                f"seed {seed:4d}: ok  frames={report.delivered_frames:3d} "
                f"corrupt_q={report.corrupt_frames} "
                f"q_peak={report.quarantined_peak} "
                f"rollbacks={report.rollbacks} "
                f"behind={report.transport_behind} "
                f"crash={report.crash_restores} "
                f"digest={report.final_digest:#010x}"
            )
    wall = time.time() - t0
    counters = health_snapshot(GLOBAL_COUNTERS)["counters"]
    print(f"\n{args.seeds - failures}/{args.seeds} campaigns clean "
          f"in {wall:.1f}s; health counters:")
    for name, value in counters.items():
        print(f"  {name:40s} {value:g}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
