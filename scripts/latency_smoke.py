#!/usr/bin/env python
"""latency smoke: the time-to-visibility plane end to end on CPU.

The CI contract (and ``make latency-smoke`` locally): drive a real serve
session open-loop with the latency plane armed, assert the plane sampled
sum-consistent stage records and marked visibility, write the artifacts
(``latency.json``, ``latency.prom``, ``why-ledger.jsonl``, ``why.json``)
for upload, check the ``obs why`` exit contract (0 clean / 1 regressed /
2 unreadable), and pin the arming overhead: the armed arm's best-of-N
wall must stay within the devprof-grade budget of the disabled arm's.
Exit nonzero on any violation — an observability regression fails CI
like a correctness one.
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: arming overhead budget: relative bound plus a small absolute floor so
#: a sub-millisecond smoke row can't fail on scheduler noise alone
OVERHEAD_FRAC = 0.02
OVERHEAD_FLOOR_S = 0.010


def fail(msg: str) -> int:
    print(f"latency-smoke FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--docs", type=int, default=4)
    parser.add_argument("--ops-per-doc", type=int, default=40)
    parser.add_argument("--seed", type=int, default=23)
    parser.add_argument("--repeats", type=int, default=5,
                        help="best-of-N walls for the overhead pin")
    parser.add_argument("--out", default="latency-artifacts")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from peritext_tpu.obs import prometheus_text
    from peritext_tpu.obs.__main__ import main as obs_main
    from peritext_tpu.obs.latency import (
        LatencyPlane, STAGES, check_sum_consistency,
    )
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.serve import SessionMux
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    d, opd = args.docs, args.ops_per_doc

    plans = []
    for w in generate_workload(args.seed, num_docs=d, ops_per_doc=opd):
        changes = [ch for log in w.values() for ch in log]
        plans.append([encode_frame(changes[i:i + 6])
                      for i in range(0, len(changes), 6)])

    def build_mux():
        session = StreamingMerge(
            num_docs=d, actors=("doc1", "doc2", "doc3"),
            slot_capacity=max(256, 4 * opd), mark_capacity=max(64, opd),
            tomb_capacity=max(128, opd),
            round_insert_capacity=128, round_delete_capacity=64,
            round_mark_capacity=64, static_rounds=True,
        )
        mux = SessionMux(session, host="latency-smoke")
        sids = []
        for doc in range(d):
            sid, verdict = mux.open_session(f"client{doc}")
            assert verdict.admitted
            sids.append(sid)
        return mux, sids

    def drive(mux, sids, read=True):
        t0 = time.perf_counter()
        for k in range(max(len(p) for p in plans)):
            for doc, plan in enumerate(plans):
                if k < len(plan):
                    mux.submit(sids[doc], plan[k])
            mux.flush()
            if read:
                mux.patches(sids[0])
        return time.perf_counter() - t0

    # -- the traced serve session -------------------------------------------
    mux, sids = build_mux()
    plane = LatencyPlane().enable()
    mux.latency_plane = plane
    drive(mux, sids)

    snap = plane.snapshot()
    (out / "latency.json").write_text(json.dumps(snap, indent=2))
    prom = prometheus_text(latency=plane)
    (out / "latency.prom").write_text(prom)

    if snap["records"] == 0:
        return fail("armed plane sampled no drain batches")
    if snap["pending_visibility"] != 0:
        return fail(f"{snap['pending_visibility']} records never marked "
                    "visible despite per-window reads")
    if snap["last"] is None or not check_sum_consistency(snap["last"]):
        return fail(f"last record not sum-consistent: {snap['last']}")
    for stage in STAGES:
        if snap["stages"][stage]["count"] == 0:
            return fail(f"stage {stage!r} histogram is empty")
        if f"peritext_latency_{stage}_seconds_count" not in prom:
            return fail(f"peritext_latency_{stage}_seconds family missing "
                        "from the exposition")
    dec = plane.decomposition()
    if not dec["sum_consistent"]:
        return fail(f"decomposition inconsistent: {dec}")

    # -- obs why exit contract ----------------------------------------------
    def ledger_rec(sha, value, stages_ms):
        return {
            "sha": sha, "config": "latency-smoke",
            "device": {"platform": "cpu", "kind": "smoke"},
            "rows": [{"row": "serve_sustained", "unit": "docs/s",
                      "value": value,
                      "latency": {"stages_ms": stages_ms,
                                  "total_ms": dec["total_ms"]}}],
        }

    base = dict(dec["stages_ms"])
    refs = [ledger_rec(f"ref{i}", 100.0, base) for i in range(5)]
    clean_path = out / "why-ledger-clean.jsonl"
    clean_path.write_text("".join(
        json.dumps(r) + "\n" for r in refs + [ledger_rec("cand", 99.0, base)]
    ))
    regressed = dict(base)
    regressed["window"] = (regressed.get("window") or 0.0) + 50.0
    why_path = out / "why-ledger.jsonl"
    why_path.write_text("".join(
        json.dumps(r) + "\n"
        for r in refs + [ledger_rec("cand", 40.0, regressed)]
    ))

    rc_clean = obs_main(["why", str(clean_path), "--tolerance", "10"])
    if rc_clean != 0:
        return fail(f"obs why exit {rc_clean} on a clean ledger (want 0)")
    rc_bad = obs_main(["why", str(why_path), "--tolerance", "10", "--json"])
    if rc_bad != 1:
        return fail(f"obs why exit {rc_bad} on a regressed ledger (want 1)")
    rc_unreadable = obs_main(["why", str(out / "missing.jsonl")])
    if rc_unreadable != 2:
        return fail(f"obs why exit {rc_unreadable} on unreadable input "
                    "(want 2)")
    from peritext_tpu.obs.latency import attribute
    report = attribute(
        [json.loads(l) for l in why_path.read_text().splitlines()],
        tolerance=0.1,
    )
    (out / "why.json").write_text(json.dumps(report, indent=2))
    if report["verdict"] != "regression-attributed" \
            or report["dominant_stage"] != "window":
        return fail(f"attribution named {report.get('dominant_stage')!r} "
                    "for a synthetic window regression")

    # -- arming overhead pin (best-of-N, identical replay) -------------------
    def best_wall(armed):
        best = float("inf")
        for _ in range(max(1, args.repeats)):
            m, s = build_mux()
            if armed:
                m.latency_plane = LatencyPlane().enable()
            best = min(best, drive(m, s))
        return best

    best_wall(False)  # one throwaway pass: every XLA variant compiles warm
    off = best_wall(False)
    on = best_wall(True)
    overhead = (on - off) / off if off else 0.0
    budget = off * OVERHEAD_FRAC + OVERHEAD_FLOOR_S
    print(f"latency-smoke: overhead best-of-{args.repeats}: "
          f"off={off * 1e3:.2f}ms on={on * 1e3:.2f}ms "
          f"({overhead * 100:+.2f}%, budget {OVERHEAD_FRAC * 100:.0f}% "
          f"+ {OVERHEAD_FLOOR_S * 1e3:.0f}ms floor)")
    if on - off > budget:
        return fail(f"arming the plane cost {(on - off) * 1e3:.2f}ms over "
                    f"the {budget * 1e3:.2f}ms budget")

    print(f"latency-smoke OK: {snap['records']} records, "
          f"force_close={ {k: v for k, v in snap['force_close'].items() if v} }, "
          f"slo_burn={snap['slo']['burn_rate']}, artifacts in {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
