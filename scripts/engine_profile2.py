"""Fine-grained engine attribution with HONEST syncs (np.asarray fetch;
block_until_ready does not block on the axon platform).

Measures: bare sync RTT, each staged round-apply individually, the chained
applies, and the digest program — so the 0.35 s engine pass decomposes into
launch/compute/sync terms instead of guesses.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def main(docs=2048, rounds=4, opd=192):
    import jax
    import jax.numpy as jnp

    from bench import build_arrival
    from peritext_tpu.ops.kernel import apply_batch_compact_jit
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.parallel.streaming import (
        StreamingMerge, _resolve_block_digest_jit,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=0, num_docs=docs, ops_per_doc=opd)
    arrival, _ = build_arrival(workloads, rounds, 0)
    captured = []
    s = StreamingMerge(
        num_docs=docs, actors=("doc1", "doc2", "doc3"),
        slot_capacity=384, mark_capacity=96, tomb_capacity=384,
        round_insert_capacity=256, round_delete_capacity=128,
        round_mark_capacity=128,
    )
    s._capture_rounds = captured
    for r in range(rounds):
        s.ingest_frames((doc, b[r]) for doc, b in enumerate(arrival)
                        if r < len(b))
        s.drain()
    expected = s.digest()

    state0 = jax.device_put(
        empty_docs(s._padded_docs, 384, 96, tomb_capacity=384))
    staged = [
        ((tuple(jax.device_put(np.asarray(c)) for c in counts),
          ins, dels, mk, mp), widths, loop_slots)
        for (counts, ins, dels, mk, mp), widths, loop_slots in captured
    ]
    print("round widths:", [(w, ls) for _, w, ls in staged])
    tables = s._digest_tables(0, s._padded_docs)
    row_mask = jnp.ones(s._padded_docs, bool)

    def sync(st):
        return np.asarray(st.num_slots if hasattr(st, "num_slots") else st)

    # warm every executable
    st = state0
    for (c, i, dl, mk, mp), w, ls in staged:
        st = apply_batch_compact_jit(st, c, i, dl, mk, mp, widths=w,
                                     insert_loop_slots=ls)
    sync(st)
    resolved, per_doc = _resolve_block_digest_jit(
        st, s.comment_capacity, row_mask, *tables)
    assert int(np.asarray(per_doc).sum(dtype=np.uint32)) == expected

    # bare sync RTT on an already-materialized tiny array
    tiny = jax.jit(lambda x: x + 1)(jnp.zeros(8, jnp.int32))
    np.asarray(tiny)
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny)
        rtts.append(time.perf_counter() - t0)
    print(f"bare fetch of ready tiny array: {min(rtts)*1e3:.1f} ms")
    rtts = []
    for _ in range(5):
        y = jax.jit(lambda x: x + 1)(tiny)
        t0 = time.perf_counter()
        np.asarray(y)
        rtts.append(time.perf_counter() - t0)
    print(f"dispatch+fetch tiny:            {min(rtts)*1e3:.1f} ms")

    # each staged round individually, honest sync
    for k, ((c, i, dl, mk, mp), w, ls) in enumerate(staged):
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = apply_batch_compact_jit(state0, c, i, dl, mk, mp, widths=w,
                                          insert_loop_slots=ls)
            sync(out)
            ts.append(time.perf_counter() - t0)
        print(f"round {k} apply (dispatch+sync): {min(ts)*1e3:7.1f} ms  "
              f"widths={w}")

    # chained applies, single sync
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        st = state0
        for (c, i, dl, mk, mp), w, ls in staged:
            st = apply_batch_compact_jit(st, c, i, dl, mk, mp, widths=w,
                                         insert_loop_slots=ls)
        sync(st)
        ts.append(time.perf_counter() - t0)
    print(f"chained {len(staged)} applies + sync:   {min(ts)*1e3:7.1f} ms")

    # digest alone on the converged state
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _, per_doc = _resolve_block_digest_jit(
            st, s.comment_capacity, row_mask, *tables)
        np.asarray(per_doc)
        ts.append(time.perf_counter() - t0)
    print(f"digest (dispatch+sync):         {min(ts)*1e3:7.1f} ms")


if __name__ == "__main__":
    main()
