"""Same-run A/B: scatter vs gather _append_rows inside the batch apply.

Round 5 rewrote the mark/tomb append as gather+select; cross-run absolute
timings moved (shared chip), so this pins the comparison in one process.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def scatter_append(table, count, rows, rows_count):
    import jax.numpy as jnp

    single = not isinstance(table, dict)
    tables = {"_": table} if single else table
    new_rows = {"_": rows} if single else rows
    cap = next(iter(tables.values())).shape[0]
    km = next(iter(new_rows.values())).shape[0]
    src = jnp.arange(km, dtype=jnp.int32)
    dst = count + src
    valid = src < rows_count
    dst = jnp.where(valid, dst, cap)
    out = {c: tables[c].at[dst].set(new_rows[c], mode="drop") for c in tables}
    overflow = count + rows_count > cap
    new_count = jnp.minimum(count + rows_count, cap)
    if single:
        return out["_"], new_count, overflow
    return out, new_count, overflow


def main():
    import jax

    from peritext_tpu.ops import kernel
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.testing.synth import synth_streams, synth_total_ops

    d, k = 8192, 256
    ki, kd = int(k * 0.7), int(k * 0.15)
    km = k - ki - kd
    streams = synth_streams(d, inserts_per_doc=ki, deletes_per_doc=kd,
                            marks_per_doc=km, seed=0)
    total = synth_total_ops(streams)
    state0 = jax.device_put(empty_docs(d, 384, max(96, km),
                                       tomb_capacity=max(kd, 8)))
    ops_dev = jax.device_put(streams)
    gather_append = kernel._append_rows

    def timed(append_impl, reps=6):
        kernel._append_rows = append_impl
        fn = jax.jit(lambda st, ops: kernel.apply_batch(
            st, ops, insert_impl="pallas", insert_loop_slots=ki))
        out = fn(state0, ops_dev)
        np.asarray(out.num_slots)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(state0, ops_dev)
        np.asarray(out.num_slots)
        return (time.perf_counter() - t0) / reps

    for name, impl in (("gather", gather_append), ("scatter", scatter_append),
                       ("gather2", gather_append), ("scatter2", scatter_append)):
        t = timed(impl)
        print(f"{name:8s}: {t*1e3:7.2f} ms/apply, {total/t/1e6:6.1f} M ops/s")
    kernel._append_rows = gather_append


if __name__ == "__main__":
    main()
