#!/usr/bin/env python
"""fleet smoke: an in-process multi-host partition/heal episode.

The CI contract (and ``make fleet`` locally): run a real N-host
ReplicaServer fleet through the chaos harness's asymmetric-partition
schedule — host0 hears every peer's frontier but every reply is cut, one
link flaps, the heal leaves the largest-lag link slow — and assert

* host0's ConvergenceMonitor learned its true per-peer lag,
* ``peritext_convergence_lag_ops`` was live in ``/metrics`` mid-episode,
* the first post-heal gossip round followed behind-ness priority,
* the fleet drained to identical fleet-wide store digests,

then run the seeded same-frontier/different-digest injection and assert it
reports as a DIVERGENCE incident (counter + flight-recorder dump), never
plain lag.  Artifacts (``fleet-report.json``, host0's convergence snapshot,
the divergence flight dump) are written for upload; the convergence report
renders via ``python -m peritext_tpu.obs fleet``.  Exit nonzero on any
violation — a convergence-observability regression fails CI like a
correctness one.
"""

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--hosts", type=int, default=4)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--out", default="fleet-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    from peritext_tpu.obs.__main__ import main as obs_main
    from peritext_tpu.testing.chaos import (
        run_divergence_injection,
        run_fleet_chaos,
    )

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    report = run_fleet_chaos(args.seed, hosts=args.hosts)
    (out / "fleet-report.json").write_text(
        json.dumps(report.to_json(), indent=1)
    )
    print(f"fleet episode: {args.hosts} hosts, "
          f"lag {sum(report.expected_lag.values())} ops at heal, "
          f"drained {report.ops_drained} ops in "
          f"{report.heal_rounds} round(s) / {report.heal_seconds:.2f}s, "
          f"heal order {report.heal_order}")
    if not (report.converged and report.lag_gauge_seen):
        print("fleet smoke: episode oracles failed", file=sys.stderr)
        return 1

    evidence = run_divergence_injection(args.seed, dump_dir=out / "flight")
    (out / "divergence.json").write_text(json.dumps(evidence, indent=1))
    print(f"divergence injection: incident reported, dump {evidence['dump']}")

    # a convergence snapshot the fleet CLI can render (the healed fleet:
    # the command must exit 0 = converged, and the table must print)
    conv = out / "convergence.json"
    conv.write_text(json.dumps({
        "host": "fleet-smoke",
        "rounds": report.heal_rounds,
        "peers": {
            name: {
                "ops_behind": 0, "ops_ahead": 0,
                "peak_ops_behind": report.expected_lag[name],
                "staleness_rounds": 0, "exchanges": 1, "failures": 0,
                "divergent": False, "last_outcome": "converged",
            } for name in report.heal_order
        },
        "total_lag_ops": 0,
        "divergence_incidents": 0,
        "divergent_peers": [],
    }))
    rc = obs_main(["fleet", str(conv)])
    if rc != 0:
        print(f"fleet smoke: obs fleet view exited {rc}", file=sys.stderr)
        return 1
    print(f"fleet smoke OK — artifacts in {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
