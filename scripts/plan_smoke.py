#!/usr/bin/env python
"""device-as-OS planner smoke: the cross-tenant fusion + closed-loop
planner CI contract (and ``make plan-smoke``).

Asserts, on CPU, the promises ISSUE 13 makes:

* **one program per window** — 32 one-doc tenants fused onto one shared
  ``static_rounds`` lane commit every batching window as ONE staged
  device program (dispatch-counter deltas); sparse windows ride the
  multi-tenant offset-plane staged form; dispatch amortization vs the
  per-session twin fleet is >= 8x;
* **byte equality / isolation** — every tenant's patch stream and
  rendered spans bit-equal to its standalone twin's (documents are
  independent CRDTs on disjoint doc rows — fusion must be invisible);
* **zero steady-state compiles** — a fresh fused group replaying the
  same window plan dispatches only already-compiled staged programs
  (RecompileSentinel);
* **closed loop** — the devprof snapshot captured DURING the fused run
  (``capture_costs`` on) feeds ``plan.propose()``: the proposal is
  deterministic (two calls, identical JSON), the ``obs plan`` CLI obeys
  its exit-code contract (0/1 on the tolerance band, 2 on garbage), and
  the proposed statics REPLAY through a fresh fused group byte-equal to
  the standalone oracle — planner advice validates before anyone
  re-pins a static.

Artifacts (``plan-report.json``, the devprof snapshot, the proposal)
are written for upload.  Exit nonzero on any violation.
"""

import argparse
import contextlib
import io
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ACTORS = ("doc1", "doc2", "doc3")


def _frame_plans(names, windows, seed, opd):
    """One workload per tenant, split causally across ``windows`` frames
    (striping one sorted change list keeps (actor, seq) causality — two
    independently seeded workloads into one doc would not replay)."""
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=seed, num_docs=len(names),
                                  ops_per_doc=opd)
    plans = {}
    for name, w in zip(names, workloads):
        changes = sorted((ch for log in w.values() for ch in log),
                         key=lambda c: (c.actor, c.seq))
        plans[name] = [
            encode_frame(changes[i::windows]) for i in range(windows)
        ]
    return plans


def _window_plan(names, frame_plans, windows):
    """Alternating full and sparse windows (the sparse ones exercise the
    offset-plane multi-tenant staged form), leftovers in a final full
    window — same discipline as the ``serve-fused`` bench row."""
    plan = []
    cursor = {n: 0 for n in names}
    for w in range(windows):
        active = list(names) if w % 2 == 0 else names[(w // 2) % 4::4]
        step = []
        for n in active:
            if cursor[n] < windows:
                step.append((n, frame_plans[n][cursor[n]]))
                cursor[n] += 1
        plan.append(step)
    tail = [(n, frame_plans[n][c])
            for n in names for c in range(cursor[n], windows)]
    if tail:
        plan.append(tail)
    return plan


def _build_group(names, session_kw):
    from peritext_tpu.plan.fusion import TenantSpec
    from peritext_tpu.serve import FusedMuxGroup, default_lane_factory

    group = FusedMuxGroup(
        [TenantSpec(tenant=n, docs=1) for n in names],
        default_lane_factory(ACTORS, **session_kw),
        host="plan-smoke",
    )
    sids = {}
    for n in names:
        sid, verdict = group.open_session(n, "client")
        assert verdict.admitted, verdict
        sids[n] = sid
    return group, sids


def _build_solo(names, session_kw):
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.serve import SessionMux

    muxes, sids = {}, {}
    for n in names:
        mux = SessionMux(
            StreamingMerge(num_docs=1, actors=ACTORS, static_rounds=True,
                           **session_kw),
            host="plan-smoke-solo",
        )
        sid, verdict = mux.open_session("client")
        assert verdict.admitted, verdict
        muxes[n], sids[n] = mux, sid
    return muxes, sids


def _drive_group(group, sids, plan):
    from peritext_tpu.obs import GLOBAL_COUNTERS

    d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
    for step in plan:
        for n, frame in step:
            verdict = group.submit(n, sids[n], frame)
            assert verdict.admitted, verdict
        group.flush()
    return int(GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0)


def _drive_solo(muxes, sids, plan):
    from peritext_tpu.obs import GLOBAL_COUNTERS

    d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
    for step in plan:
        touched = []
        for n, frame in step:
            verdict = muxes[n].submit(sids[n], frame)
            assert verdict.admitted, verdict
            touched.append(n)
        for n in dict.fromkeys(touched):
            muxes[n].flush()
    return int(GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tenants", type=int, default=32)
    parser.add_argument("--windows", type=int, default=6)
    parser.add_argument("--ops-per-doc", type=int, default=24)
    parser.add_argument("--seed", type=int, default=29)
    parser.add_argument("--out", default="plan-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    from peritext_tpu.obs import GLOBAL_DEVPROF
    from peritext_tpu.obs.__main__ import main as obs_main
    from peritext_tpu.observability import RecompileSentinel
    from peritext_tpu.plan import propose

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    names = [f"tenant{i:03d}" for i in range(args.tenants)]
    frame_plans = _frame_plans(names, args.windows, args.seed,
                               args.ops_per_doc)
    plan = _window_plan(names, frame_plans, args.windows)
    session_kw = dict(
        slot_capacity=128, mark_capacity=64, tomb_capacity=96,
        round_insert_capacity=32, round_delete_capacity=16,
        round_mark_capacity=16,
    )
    report = {"tenants": args.tenants, "windows": len(plan),
              "seed": args.seed}

    GLOBAL_DEVPROF.reset()
    GLOBAL_DEVPROF.enable(capture_costs=True)
    try:
        # -- fused vs per-session: one program per window, byte equality
        group, gsids = _build_group(names, session_kw)
        fused_dispatches = _drive_group(group, gsids, plan)
        muxes, ssids = _build_solo(names, session_kw)
        solo_dispatches = _drive_solo(muxes, ssids, plan)
        assert fused_dispatches == len(plan), (
            f"expected one staged program per window: "
            f"{fused_dispatches} dispatches over {len(plan)} windows"
        )
        amortization = solo_dispatches / fused_dispatches
        assert amortization >= 8.0, (
            f"dispatch amortization {amortization:.2f}x < 8x "
            f"({solo_dispatches} per-session vs {fused_dispatches} fused)"
        )
        solo_patches, solo_spans = {}, {}
        for n in names:
            solo_patches[n] = muxes[n].patches(ssids[n])
            solo_spans[n] = muxes[n].read(ssids[n])
            assert group.patches(n, gsids[n]) == solo_patches[n], (
                f"fused/unfused patch divergence for {n}")
            assert group.read(n, gsids[n]) == solo_spans[n], (
                f"fused/unfused span divergence for {n}")
        fusion = group.fusion_snapshot()
        assert fusion["grouped"] and fusion["lanes"] == 1, fusion
        report["fused_dispatches"] = fused_dispatches
        report["per_session_dispatches"] = solo_dispatches
        report["amortization_x"] = round(amortization, 2)
        report["fusion"] = fusion

        # -- zero steady-state compiles on a repeat window plan
        with RecompileSentinel() as sentinel:
            sentinel.mark()
            warm, wsids = _build_group(names, session_kw)
            _drive_group(warm, wsids, plan)
            sentinel.assert_steady_state(
                "fused multi-tenant repeat window plan")
        for n in names:
            assert warm.read(n, wsids[n]) == solo_spans[n]
        report["steady_state_compiles"] = 0
    finally:
        GLOBAL_DEVPROF.disable()

    snap = GLOBAL_DEVPROF.snapshot()
    assert snap["sites"], "devprof captured no dispatch sites"
    assert snap["occupancy"], "devprof captured no occupancy rows"
    report["devprof_sites"] = sorted(snap["sites"])
    snap_path = out / "devprof-snapshot.json"
    snap_path.write_text(json.dumps(snap, indent=2, sort_keys=True))

    # -- closed loop: deterministic proposal from the captured snapshot
    proposal = propose(snap)
    assert proposal.to_json() == propose(snap).to_json(), (
        "propose() must be a pure function of the snapshot")
    report["proposal"] = proposal.to_json()
    report["beats_current"] = proposal.beats_current()
    (out / "proposal.json").write_text(
        json.dumps(report["proposal"], indent=2))

    # -- the operator surface obeys its exit-code contract
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = obs_main(["plan", str(snap_path), "--json"])
    assert rc == (1 if proposal.beats_current() else 0), (
        f"obs plan exit {rc} disagrees with "
        f"beats_current={proposal.beats_current()}")
    cli_body = json.loads(buf.getvalue())
    assert cli_body["proposal"] == report["proposal"]["proposal"], (
        "CLI proposal diverges from the library proposal")
    garbage = out / "garbage.json"
    garbage.write_text("{not json")
    with contextlib.redirect_stdout(io.StringIO()), \
            contextlib.redirect_stderr(io.StringIO()):
        assert obs_main(["plan", str(garbage), "--json"]) == 2
    report["cli_exit"] = rc

    # -- replay the proposed statics: advice must stay byte-equal before
    #    anyone re-pins a static (smaller widths just mean more rounds)
    replay_kw = dict(
        session_kw,
        slot_capacity=max(proposal.slot_capacity, 64),
        round_insert_capacity=proposal.insert_width,
        round_delete_capacity=proposal.delete_width,
        round_mark_capacity=proposal.mark_width,
    )
    replay, rsids = _build_group(names, replay_kw)
    _drive_group(replay, rsids, plan)
    for n in names:
        assert replay.patches(n, rsids[n]) == solo_patches[n], (
            f"proposed statics diverge from the oracle for {n}")
        assert replay.read(n, rsids[n]) == solo_spans[n]
    report["replay_byte_equal"] = True

    (out / "plan-report.json").write_text(json.dumps(report, indent=2))
    print(json.dumps({
        "ok": True,
        "amortization_x": report["amortization_x"],
        "fused_dispatches": fused_dispatches,
        "per_session_dispatches": solo_dispatches,
        "beats_current": report["beats_current"],
        "replay_byte_equal": True,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
