#!/usr/bin/env python
"""Generate the ProseMirror conformance fixtures (tests/pm_fixtures/).

Each scenario's EDITS are authored directly in ProseMirror's wire schema
(``Step.toJSON()`` — the exact JSON a real PM client posts through the
bridge); this script replays them through two bridged editors (scalar
backend) and records the converged document as ``Node.toJSON()`` of the
reference schema.  The conformance tests then replay the fixtures from JSON
alone — against BOTH backends — asserting byte-equal convergence, so the
fixtures pin the full PM-JSON -> bridge -> CRDT -> patch -> PM-JSON loop.

Re-run after intentionally changing merge semantics:
    python scripts/gen_pm_fixtures.py
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

FIXTURES = Path(__file__).resolve().parent.parent / "tests" / "pm_fixtures"

INITIAL = "The Peritext editor"


def replace(frm, to, text=None, marks=None):
    step = {"stepType": "replace", "from": frm, "to": to}
    if text:
        node = {"type": "text", "text": text}
        if marks:
            node["marks"] = marks
        step["slice"] = {"content": [node]}
    return step


def add_mark(frm, to, mark_type, attrs=None):
    mark = {"type": mark_type}
    if attrs:
        mark["attrs"] = attrs
    return {"stepType": "addMark", "from": frm, "to": to, "mark": mark}


def remove_mark(frm, to, mark_type, attrs=None):
    mark = {"type": mark_type}
    if attrs:
        mark["attrs"] = attrs
    return {"stepType": "removeMark", "from": frm, "to": to, "mark": mark}


def typing(editor, pos, text):
    """Per-keystroke replace steps (how PM delivers real typing)."""
    return [
        {"editor": editor, "steps": [replace(pos + i, pos + i, ch)]}
        for i, ch in enumerate(text)
    ]


SCENARIOS = {
    # interactive typing from both sides, merged mid-stream
    "typing": {
        "initial": INITIAL,
        "events": (
            typing("alice", 20, " rocks")
            + [{"sync": True}]
            + typing("bob", 1, ">> ")      # bob at the front...
            + typing("alice", 26, "!")     # ...alice at the end, unsynced
            + [{"sync": True}]
        ),
    },
    # the reference's headline conflict: overlapping bold and italic
    "format_overlap": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 13, "strong")]},
            {"editor": "bob", "steps": [add_mark(5, 20, "em")]},
            {"sync": True},
        ],
    },
    # concurrent links over an overlap: one winner per character (LWW)
    "link_conflict": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice",
             "steps": [add_mark(1, 10, "link", {"url": "https://inkandswitch.com"})]},
            {"editor": "bob",
             "steps": [add_mark(5, 15, "link", {"url": "https://example.org"})]},
            {"sync": True},
        ],
    },
    # comments are an id-keyed set: concurrent adds coexist, removal by id
    "comments": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 8, "comment", {"id": "c-alice"})]},
            {"editor": "bob", "steps": [add_mark(4, 12, "comment", {"id": "c-bob"})]},
            {"sync": True},
            {"editor": "alice", "steps": [remove_mark(1, 8, "comment", {"id": "c-alice"})]},
            {"sync": True},
        ],
    },
    # select-and-type (content-bearing ReplaceStep) vs a concurrent delete
    "replace_selection": {
        "initial": INITIAL,
        "events": [
            {"editor": "bob", "steps": [replace(5, 13, "Micromerge")]},
            {"editor": "alice", "steps": [replace(1, 5, "")]},
            {"sync": True},
        ],
    },
    # unbold a sub-range while the other side types inside the bold span
    "unbold_while_typing": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 13, "strong")]},
            {"sync": True},
            {"editor": "bob", "steps": [remove_mark(4, 9, "strong")]},
            *typing("alice", 5, "xy"),
            {"sync": True},
        ],
    },
    # marked typing: PM sends the stored-marks set inside the replace slice
    "typing_with_marks": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 4, "strong")]},
            {"sync": True},
            {"editor": "bob",
             "steps": [replace(4, 4, "se", [{"type": "strong"}])]},
            {"sync": True},
        ],
    },
    # replace-with-content ON a marked range (delete+insert through the
    # bridge, reference src/bridge.ts:428-444) while the other side types
    # inside the same bold span — round-4 review: this step shape appeared
    # in only one fixture
    "replace_marked_range": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 13, "strong")]},
            {"sync": True},
            {"editor": "bob",
             "steps": [replace(4, 9, "plain")]},
            *typing("alice", 6, "zz"),
            {"sync": True},
        ],
    },
    # removeMark whose range spans text a concurrent editor deleted — the
    # anchors must resolve against the CRDT positions, not the PM indices
    "removemark_spanning_deletion": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 16, "strong")]},
            {"sync": True},
            {"editor": "alice", "steps": [replace(5, 10, "")]},
            {"editor": "bob", "steps": [remove_mark(3, 14, "strong")]},
            {"sync": True},
        ],
    },
}

# External provenance per fixture (VERDICT r4 task 5): the step/doc JSON
# SHAPES follow prosemirror-transform's published wire schema
# (Step.toJSON: stepType/from/to + slice{content|openStart|openEnd} for
# ReplaceStep, mark{type,attrs} for Add/RemoveMarkStep — documented in the
# prosemirror-transform README and Step.fromJSON contract) and
# prosemirror-model's Node.toJSON.  No network egress or node runtime
# exists in this image, so upstream test FILES cannot be vendored; each
# entry instead names the documented upstream construct the scenario
# mirrors, and the expected documents are pinned by replaying the steps
# through this repo's bridge (see README "What a browser would add").
SOURCES = {
    "typing": "prosemirror-transform ReplaceStep one-char insert shape "
              "(tr.insertText -> Step.toJSON, PM ref manual); scenario: "
              "reference two-editors demo typing loop",
    "format_overlap": "AddMarkStep shape per prosemirror-transform "
                      "Step.toJSON; scenario: Peritext paper fig. 'bold "
                      "vs italic overlap' (reference essay.tsx)",
    "link_conflict": "AddMarkStep with attrs per prosemirror-transform; "
                     "scenario: Peritext paper link-conflict example "
                     "(reference src/schema.ts link allowMultiple=false)",
    "comments": "AddMark/RemoveMarkStep with id attrs; scenario: reference "
                "comment sidebar (src/schema.ts comment allowMultiple)",
    "replace_selection": "ReplaceStep select-and-type + pure-delete shapes "
                         "(prosemirror-transform tr.replaceWith/tr.delete "
                         "Step.toJSON)",
    "unbold_while_typing": "RemoveMarkStep sub-range shape; scenario: "
                           "Peritext paper unbold-while-typing example",
    "typing_with_marks": "ReplaceStep slice with marks (PM storedMarks "
                         "typing emits marked text nodes in the slice)",
    "replace_marked_range": "ReplaceStep with content over a marked range "
                            "(delete+insert, reference src/bridge.ts:"
                            "428-444); round-4 review gap",
    "removemark_spanning_deletion": "RemoveMarkStep spanning a concurrent "
                                    "deletion; round-4 review gap",
}


def run_scenario(spec):
    from peritext_tpu.bridge.bridge import create_editor, initialize_docs
    from peritext_tpu.bridge.pm import editor_doc_to_pm, transaction_from_pm
    from peritext_tpu.parallel.pubsub import Publisher

    pub = Publisher()
    editors = {
        "alice": create_editor("alice", pub),
        "bob": create_editor("bob", pub),
    }
    initialize_docs([editors["alice"], editors["bob"]], spec["initial"])
    for event in spec["events"]:
        if event.get("sync"):
            for ed in editors.values():
                ed.sync()
            continue
        ed = editors[event["editor"]]
        ed.dispatch(transaction_from_pm(event["steps"]))
    for ed in editors.values():
        ed.sync()
    views = {name: editor_doc_to_pm(ed.view) for name, ed in editors.items()}
    assert views["alice"] == views["bob"], "scenario did not converge"
    return views["alice"], editors["alice"].text


def main():
    FIXTURES.mkdir(exist_ok=True)
    for name, spec in SCENARIOS.items():
        expected_doc, expected_text = run_scenario(spec)
        out = {"source": SOURCES[name], **spec}
        out["expected_doc"] = expected_doc
        out["expected_text"] = expected_text
        path = FIXTURES / f"{name}.json"
        path.write_text(json.dumps(out, indent=1) + "\n")
        print(f"{name}: {expected_text!r}")


if __name__ == "__main__":
    main()
