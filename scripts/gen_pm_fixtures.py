#!/usr/bin/env python
"""Generate the ProseMirror conformance fixtures (tests/pm_fixtures/).

Each scenario's EDITS are authored directly in ProseMirror's wire schema
(``Step.toJSON()`` — the exact JSON a real PM client posts through the
bridge); this script replays them through two bridged editors (scalar
backend) and records the converged document as ``Node.toJSON()`` of the
reference schema.  The conformance tests then replay the fixtures from JSON
alone — against BOTH backends — asserting byte-equal convergence, so the
fixtures pin the full PM-JSON -> bridge -> CRDT -> patch -> PM-JSON loop.

Re-run after intentionally changing merge semantics:
    python scripts/gen_pm_fixtures.py
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

FIXTURES = Path(__file__).resolve().parent.parent / "tests" / "pm_fixtures"

INITIAL = "The Peritext editor"


def replace(frm, to, text=None, marks=None):
    step = {"stepType": "replace", "from": frm, "to": to}
    if text:
        node = {"type": "text", "text": text}
        if marks:
            node["marks"] = marks
        step["slice"] = {"content": [node]}
    return step


def add_mark(frm, to, mark_type, attrs=None):
    mark = {"type": mark_type}
    if attrs:
        mark["attrs"] = attrs
    return {"stepType": "addMark", "from": frm, "to": to, "mark": mark}


def remove_mark(frm, to, mark_type, attrs=None):
    mark = {"type": mark_type}
    if attrs:
        mark["attrs"] = attrs
    return {"stepType": "removeMark", "from": frm, "to": to, "mark": mark}


def typing(editor, pos, text):
    """Per-keystroke replace steps (how PM delivers real typing)."""
    return [
        {"editor": editor, "steps": [replace(pos + i, pos + i, ch)]}
        for i, ch in enumerate(text)
    ]


SCENARIOS = {
    # interactive typing from both sides, merged mid-stream
    "typing": {
        "initial": INITIAL,
        "events": (
            typing("alice", 20, " rocks")
            + [{"sync": True}]
            + typing("bob", 1, ">> ")      # bob at the front...
            + typing("alice", 26, "!")     # ...alice at the end, unsynced
            + [{"sync": True}]
        ),
    },
    # the reference's headline conflict: overlapping bold and italic
    "format_overlap": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 13, "strong")]},
            {"editor": "bob", "steps": [add_mark(5, 20, "em")]},
            {"sync": True},
        ],
    },
    # concurrent links over an overlap: one winner per character (LWW)
    "link_conflict": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice",
             "steps": [add_mark(1, 10, "link", {"url": "https://inkandswitch.com"})]},
            {"editor": "bob",
             "steps": [add_mark(5, 15, "link", {"url": "https://example.org"})]},
            {"sync": True},
        ],
    },
    # comments are an id-keyed set: concurrent adds coexist, removal by id
    "comments": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 8, "comment", {"id": "c-alice"})]},
            {"editor": "bob", "steps": [add_mark(4, 12, "comment", {"id": "c-bob"})]},
            {"sync": True},
            {"editor": "alice", "steps": [remove_mark(1, 8, "comment", {"id": "c-alice"})]},
            {"sync": True},
        ],
    },
    # select-and-type (content-bearing ReplaceStep) vs a concurrent delete
    "replace_selection": {
        "initial": INITIAL,
        "events": [
            {"editor": "bob", "steps": [replace(5, 13, "Micromerge")]},
            {"editor": "alice", "steps": [replace(1, 5, "")]},
            {"sync": True},
        ],
    },
    # unbold a sub-range while the other side types inside the bold span
    "unbold_while_typing": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 13, "strong")]},
            {"sync": True},
            {"editor": "bob", "steps": [remove_mark(4, 9, "strong")]},
            *typing("alice", 5, "xy"),
            {"sync": True},
        ],
    },
    # marked typing: PM sends the stored-marks set inside the replace slice
    "typing_with_marks": {
        "initial": INITIAL,
        "events": [
            {"editor": "alice", "steps": [add_mark(1, 4, "strong")]},
            {"sync": True},
            {"editor": "bob",
             "steps": [replace(4, 4, "se", [{"type": "strong"}])]},
            {"sync": True},
        ],
    },
}


def run_scenario(spec):
    from peritext_tpu.bridge.bridge import create_editor, initialize_docs
    from peritext_tpu.bridge.pm import editor_doc_to_pm, transaction_from_pm
    from peritext_tpu.parallel.pubsub import Publisher

    pub = Publisher()
    editors = {
        "alice": create_editor("alice", pub),
        "bob": create_editor("bob", pub),
    }
    initialize_docs([editors["alice"], editors["bob"]], spec["initial"])
    for event in spec["events"]:
        if event.get("sync"):
            for ed in editors.values():
                ed.sync()
            continue
        ed = editors[event["editor"]]
        ed.dispatch(transaction_from_pm(event["steps"]))
    for ed in editors.values():
        ed.sync()
    views = {name: editor_doc_to_pm(ed.view) for name, ed in editors.items()}
    assert views["alice"] == views["bob"], "scenario did not converge"
    return views["alice"], editors["alice"].text


def main():
    FIXTURES.mkdir(exist_ok=True)
    for name, spec in SCENARIOS.items():
        expected_doc, expected_text = run_scenario(spec)
        out = dict(spec)
        out["expected_doc"] = expected_doc
        out["expected_text"] = expected_text
        path = FIXTURES / f"{name}.json"
        path.write_text(json.dumps(out, indent=1) + "\n")
        print(f"{name}: {expected_text!r}")


if __name__ == "__main__":
    main()
