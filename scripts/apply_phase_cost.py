"""Per-phase cost of the round-apply program (round 5).

Times apply_batch_compact_jit at 2048x384 with one stream width raised at
a time (others at the 8 floor), steady-state (8 chained dispatches, one
sync), so the expensive phase is measured rather than guessed.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def main():
    import jax

    from peritext_tpu.ops.encode import MARK_COLS
    from peritext_tpu.ops.kernel import apply_batch_compact_jit
    from peritext_tpu.ops.packed import MAP_STREAM_COLS, empty_docs

    docs, slots, marks = 2048, 384, 96
    base = jax.device_put(empty_docs(docs, slots, marks, tomb_capacity=slots))

    def timed(widths, loop_slots, counts_v):
        ki, kd, km, kp = widths
        n_i = np.full(docs, counts_v[0], np.int32)
        n_d = np.full(docs, counts_v[1], np.int32)
        n_m = np.full(docs, counts_v[2], np.int32)
        n_p = np.full(docs, counts_v[3], np.int32)
        counts = tuple(jax.device_put(x) for x in (n_i, n_d, n_m, n_p))
        ins = tuple(jax.device_put(np.zeros(max(int(n_i.sum()), 1), np.int32))
                    for _ in range(3))
        dels = jax.device_put(np.zeros(max(int(n_d.sum()), 1), np.int32))
        mk = {c: jax.device_put(np.zeros(max(int(n_m.sum()), 1), np.int32))
              for c in MARK_COLS}
        mp = {c: jax.device_put(np.zeros(max(int(n_p.sum()), 1), np.int32))
              for c in MAP_STREAM_COLS}

        def one(st):
            return apply_batch_compact_jit(
                st, counts, ins, dels, mk, mp, widths=widths,
                insert_loop_slots=loop_slots)

        st = one(base)
        np.asarray(st.num_slots)
        reps = 8
        t0 = time.perf_counter()
        st = base
        for _ in range(reps):
            st = one(st)
        np.asarray(st.num_slots)
        return (time.perf_counter() - t0) / reps

    floor = (8, 8, 8, 8)
    print(f"floor {floor} win=64:      {timed(floor, 64, (4,2,2,1))*1e3:7.2f} ms")
    print(f"ins   (128,8,8,8) win=128: {timed((128,8,8,8), 128, (64,2,2,1))*1e3:7.2f} ms")
    print(f"ins   (128,8,8,8) win=384: {timed((128,8,8,8), None, (64,2,2,1))*1e3:7.2f} ms")
    print(f"del   (8,128,8,8) win=64:  {timed((8,128,8,8), 64, (4,64,2,1))*1e3:7.2f} ms")
    print(f"mark  (8,8,128,8) win=64:  {timed((8,8,128,8), 64, (4,2,64,1))*1e3:7.2f} ms")
    print(f"map   (8,8,8,16)  win=64:  {timed((8,8,8,16), 64, (4,2,2,8))*1e3:7.2f} ms")
    print(f"r3mix (128,128,128,8) win=128: {timed((128,128,128,8), 128, (64,32,32,1))*1e3:7.2f} ms")


if __name__ == "__main__" and "--floor" not in sys.argv:
    main()


def floor_probe():
    """What is the ~18 ms per-program floor made of?"""
    import jax
    import jax.numpy as jnp

    from peritext_tpu.ops.encode import MARK_COLS
    from peritext_tpu.ops.kernel import apply_batch_compact_jit
    from peritext_tpu.ops.packed import MAP_STREAM_COLS, empty_docs

    docs, slots, marks = 2048, 384, 96
    base = jax.device_put(empty_docs(docs, slots, marks, tomb_capacity=slots))

    def steady(fn, reps=8):
        st = fn(base)
        np.asarray(st.num_slots)
        t0 = time.perf_counter()
        st = base
        for _ in range(reps):
            st = fn(st)
        np.asarray(st.num_slots)
        return (time.perf_counter() - t0) / reps

    ident = jax.jit(lambda st: st._replace(num_slots=st.num_slots + 1))
    print(f"identity(+1 on counts):      {steady(ident)*1e3:7.2f} ms")
    touch = jax.jit(lambda st: st._replace(
        elem_id=st.elem_id + 1, char=st.char + 1,
        num_slots=st.num_slots + 1))
    print(f"touch elem+char planes:      {steady(touch)*1e3:7.2f} ms")
    touch_all = jax.jit(lambda st: type(st)(*(x + 1 if x.dtype != jnp.bool_
                                              else x for x in st)))
    print(f"touch ALL planes:            {steady(touch_all)*1e3:7.2f} ms")

    widths, loop_slots, cv = (8, 8, 8, 8), 64, (4, 2, 2, 1)
    ki, kd, km, kp = widths
    n_i = np.full(docs, cv[0], np.int32); n_d = np.full(docs, cv[1], np.int32)
    n_m = np.full(docs, cv[2], np.int32); n_p = np.full(docs, cv[3], np.int32)
    counts = tuple(jax.device_put(x) for x in (n_i, n_d, n_m, n_p))
    ins = tuple(jax.device_put(np.zeros(int(n_i.sum()), np.int32)) for _ in range(3))
    dels = jax.device_put(np.zeros(int(n_d.sum()), np.int32))
    mk = {c: jax.device_put(np.zeros(int(n_m.sum()), np.int32)) for c in MARK_COLS}
    mp = {c: jax.device_put(np.zeros(int(n_p.sum()), np.int32)) for c in MAP_STREAM_COLS}
    for impl in ("pallas", "lax"):
        fn = lambda st: apply_batch_compact_jit(
            st, counts, ins, dels, mk, mp, widths=widths,
            insert_loop_slots=loop_slots, insert_impl=impl)
        print(f"floor apply impl={impl:18s}{steady(fn)*1e3:7.2f} ms")


if __name__ == "__main__" and "--floor" in sys.argv:
    floor_probe()
