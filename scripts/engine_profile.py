"""Stage attribution for the engine-limit streaming row (VERDICT r4 task 2).

Replays the captured rounds exactly as bench.py --mode engine does, but
times the apply chain and the digest program separately (each behind its
own sync), and sweeps round depth x docs to locate the fixed-cost knee.
Run on the chip:  python scripts/engine_profile.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def measure(docs, rounds, ops_per_doc, slots=384, marks=96, passes=3,
            profile_dir=None):
    import jax
    import jax.numpy as jnp

    from bench import build_arrival
    from peritext_tpu.ops.kernel import apply_batch_compact_jit
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.parallel.streaming import (
        StreamingMerge, _resolve_block_digest_jit,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=0, num_docs=docs, ops_per_doc=ops_per_doc)
    arrival, _ = build_arrival(workloads, rounds, 0)

    captured = []
    s = StreamingMerge(
        num_docs=docs, actors=("doc1", "doc2", "doc3"),
        slot_capacity=slots, mark_capacity=marks, tomb_capacity=slots,
        round_insert_capacity=256, round_delete_capacity=128,
        round_mark_capacity=128,
    )
    s._capture_rounds = captured
    for r in range(rounds):
        s.ingest_frames((doc, batches[r]) for doc, batches in enumerate(arrival)
                        if r < len(batches))
        s.drain()
    expected = s.digest()
    assert s.overflow_count() == 0

    state0 = jax.device_put(
        empty_docs(s._padded_docs, slots, marks, tomb_capacity=slots))
    staged = [
        ((tuple(jax.device_put(np.asarray(c)) for c in counts),
          ins, dels, mk, mp), widths, loop_slots)
        for (counts, ins, dels, mk, mp), widths, loop_slots in captured
    ]
    tables = s._digest_tables(0, s._padded_docs)
    row_mask = jnp.ones(s._padded_docs, bool)

    def apply_chain():
        st = state0
        for (counts, ins, dels, mk, mp), widths, loop_slots in staged:
            st = apply_batch_compact_jit(st, counts, ins, dels, mk, mp,
                                         widths=widths,
                                         insert_loop_slots=loop_slots)
        return st

    def digest_of(st):
        _, per_doc = _resolve_block_digest_jit(
            st, s.comment_capacity, row_mask, *tables)
        return int(np.asarray(per_doc).sum(dtype=np.uint32))

    # warm
    st = apply_chain()
    assert digest_of(st) == expected

    apply_t, digest_t, total_t = [], [], []
    for _ in range(passes):
        t0 = time.perf_counter()
        st = apply_chain()
        jax.block_until_ready(st.char)
        t1 = time.perf_counter()
        dg = digest_of(st)
        t2 = time.perf_counter()
        apply_t.append(t1 - t0)
        digest_t.append(t2 - t1)
        # combined single-sync (the bench row's definition)
        t0 = time.perf_counter()
        dg = digest_of(apply_chain())
        total_t.append(time.perf_counter() - t0)
    assert dg == expected

    if profile_dir:
        import jax.profiler
        with jax.profiler.trace(profile_dir):
            digest_of(apply_chain())

    total_ops = sum(len(ch.ops) for w in workloads for log in w.values()
                    for ch in log)
    n_staged = len(staged)
    return dict(docs=docs, rounds=rounds, staged_rounds=n_staged,
                ops=total_ops,
                apply_s=round(min(apply_t), 4),
                apply_per_round_ms=round(1e3 * min(apply_t) / n_staged, 2),
                digest_s=round(min(digest_t), 4),
                total_s=round(min(total_t), 4),
                ops_per_sec=round(total_ops / min(total_t), 1))


if __name__ == "__main__":
    shapes = [(2048, 4, 192)]
    if "--sweep" in sys.argv:
        shapes = [
            (2048, 4, 192),   # the bench shape
            (2048, 1, 192),   # one big round: all ops in a single apply
            (2048, 2, 192),
            (2048, 8, 192),
            (2048, 16, 192),
            (512, 4, 192),
            (8192, 4, 192),
        ]
    prof = "--profile" in sys.argv
    for docs, rounds, opd in shapes:
        r = measure(docs, rounds, opd,
                    profile_dir="/tmp/engine_trace" if prof else None)
        print(r)
