"""Engine-limit stage attribution, consolidated (VERDICT r4 task 2 + r5).

One script, two granularities over the SAME captured-round replay that
bench.py --mode engine times:

* default (coarse): the apply chain and the digest program separately
  (each behind its own sync), sweepable over round depth x docs to locate
  the fixed-cost knee (``--sweep``).
* ``--fine``: HONEST-sync decomposition (np.asarray fetch;
  block_until_ready does not block on the axon platform) — bare sync RTT,
  each staged round-apply individually, the chained applies, and the
  digest program — so an engine pass decomposes into launch/compute/sync
  terms instead of guesses.

Both modes run under the device profiler (obs/devprof.py), so ad-hoc
profiling emits the SAME snapshot schema the perf ledger stores:
``--devprof-out PATH`` writes the shape-bucket/occupancy/memory snapshot
as JSON, and ``--ledger PATH`` appends a full ledger record (throughput
row + devprof snapshot) for `python -m peritext_tpu.obs perf`.

Run on the chip:  python scripts/engine_profile.py [--fine|--sweep]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def stage_replay(docs, rounds, opd, slots, marks, round_caps=(256, 128, 128)):
    """Build a captured-round replay: run a real streaming session with
    round capture on, pre-stage every round's device-ready inputs, and
    return everything the timing loops need."""
    import jax
    import jax.numpy as jnp

    from bench import build_arrival
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=0, num_docs=docs, ops_per_doc=opd)
    arrival, _ = build_arrival(workloads, rounds, 0)
    captured = []
    ki, kd, km = round_caps
    s = StreamingMerge(
        num_docs=docs, actors=("doc1", "doc2", "doc3"),
        slot_capacity=slots, mark_capacity=marks, tomb_capacity=slots,
        round_insert_capacity=ki, round_delete_capacity=kd,
        round_mark_capacity=km,
    )
    s._capture_rounds = captured
    for r in range(rounds):
        s.ingest_frames((doc, batches[r]) for doc, batches in enumerate(arrival)
                        if r < len(batches))
        s.drain()
    expected = s.digest()
    assert s.overflow_count() == 0

    state0 = jax.device_put(
        empty_docs(s._padded_docs, slots, marks, tomb_capacity=slots))
    staged = [
        ((tuple(jax.device_put(np.asarray(c)) for c in counts),
          ins, dels, mk, mp), widths, loop_slots)
        for (counts, ins, dels, mk, mp), widths, loop_slots in captured
    ]
    tables = s._digest_tables(0, s._padded_docs)
    row_mask = jnp.ones(s._padded_docs, bool)
    total_ops = sum(len(ch.ops) for w in workloads for log in w.values()
                    for ch in log)
    return s, staged, state0, tables, row_mask, expected, total_ops


def _apply_chain(staged, state0):
    from peritext_tpu.ops.kernel import apply_batch_compact_jit

    st = state0
    for (c, i, dl, mk, mp), w, ls in staged:
        st = apply_batch_compact_jit(st, c, i, dl, mk, mp, widths=w,
                                     insert_loop_slots=ls)
    return st


def _digest_of(s, st, tables, row_mask):
    from peritext_tpu.obs import GLOBAL_DEVPROF, note_jit_dispatch
    from peritext_tpu.parallel.streaming import _resolve_block_digest_jit

    args = (st, s.comment_capacity, row_mask, *tables)
    if GLOBAL_DEVPROF.enabled:
        note_jit_dispatch("_resolve_block_digest_jit",
                          _resolve_block_digest_jit, args)
    _, per_doc = _resolve_block_digest_jit(*args)
    return int(np.asarray(per_doc).sum(dtype=np.uint32))


def measure(docs, rounds, opd, slots=384, marks=96, passes=3,
            profile_dir=None):
    """Coarse attribution: apply chain vs digest, each behind its own sync."""
    import jax

    s, staged, state0, tables, row_mask, expected, total_ops = stage_replay(
        docs, rounds, opd, slots, marks)

    # warm
    st = _apply_chain(staged, state0)
    assert _digest_of(s, st, tables, row_mask) == expected

    apply_t, digest_t, total_t = [], [], []
    for _ in range(passes):
        t0 = time.perf_counter()
        st = _apply_chain(staged, state0)
        jax.block_until_ready(st.char)
        t1 = time.perf_counter()
        dg = _digest_of(s, st, tables, row_mask)
        t2 = time.perf_counter()
        apply_t.append(t1 - t0)
        digest_t.append(t2 - t1)
        # combined single-sync (the bench row's definition)
        t0 = time.perf_counter()
        dg = _digest_of(s, _apply_chain(staged, state0), tables, row_mask)
        total_t.append(time.perf_counter() - t0)
    assert dg == expected

    if profile_dir:
        import jax.profiler
        with jax.profiler.trace(profile_dir):
            _digest_of(s, _apply_chain(staged, state0), tables, row_mask)

    n_staged = len(staged)
    return dict(docs=docs, rounds=rounds, staged_rounds=n_staged,
                ops=total_ops,
                apply_s=round(min(apply_t), 4),
                apply_per_round_ms=round(1e3 * min(apply_t) / n_staged, 2),
                digest_s=round(min(digest_t), 4),
                total_s=round(min(total_t), 4),
                ops_per_sec=round(total_ops / min(total_t), 1))


def measure_fine(docs, rounds, opd, slots=384, marks=96):
    """Fine attribution with HONEST syncs: bare RTT, per-round applies,
    chained applies, digest (the old engine_profile2)."""
    import jax
    import jax.numpy as jnp

    from peritext_tpu.ops.kernel import apply_batch_compact_jit

    s, staged, state0, tables, row_mask, expected, total_ops = stage_replay(
        docs, rounds, opd, slots, marks)
    print("round widths:", [(w, ls) for _, w, ls in staged])

    def sync(st):
        return np.asarray(st.num_slots if hasattr(st, "num_slots") else st)

    # warm every executable
    st = _apply_chain(staged, state0)
    sync(st)
    assert _digest_of(s, st, tables, row_mask) == expected

    # bare sync RTT on an already-materialized tiny array
    tiny = jax.jit(lambda x: x + 1)(jnp.zeros(8, jnp.int32))
    np.asarray(tiny)
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny)
        rtts.append(time.perf_counter() - t0)
    print(f"bare fetch of ready tiny array: {min(rtts)*1e3:.1f} ms")
    rtts = []
    for _ in range(5):
        y = jax.jit(lambda x: x + 1)(tiny)
        t0 = time.perf_counter()
        np.asarray(y)
        rtts.append(time.perf_counter() - t0)
    print(f"dispatch+fetch tiny:            {min(rtts)*1e3:.1f} ms")

    # each staged round individually, honest sync
    for k, ((c, i, dl, mk, mp), w, ls) in enumerate(staged):
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = apply_batch_compact_jit(state0, c, i, dl, mk, mp, widths=w,
                                          insert_loop_slots=ls)
            sync(out)
            ts.append(time.perf_counter() - t0)
        print(f"round {k} apply (dispatch+sync): {min(ts)*1e3:7.1f} ms  "
              f"widths={w}")

    # chained applies, single sync
    chain_ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        st = _apply_chain(staged, state0)
        sync(st)
        chain_ts.append(time.perf_counter() - t0)
    print(f"chained {len(staged)} applies + sync:   {min(chain_ts)*1e3:7.1f} ms")

    # digest alone on the converged state
    digest_ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        dg = _digest_of(s, st, tables, row_mask)
        digest_ts.append(time.perf_counter() - t0)
    assert dg == expected
    print(f"digest (dispatch+sync):         {min(digest_ts)*1e3:7.1f} ms")
    # the pass total is apply chain + digest — reporting the digest loop
    # alone would overstate engine throughput several-fold in the ledger
    total = min(chain_ts) + min(digest_ts)
    return dict(docs=docs, rounds=rounds, staged_rounds=len(staged),
                ops=total_ops, mode="fine",
                apply_s=round(min(chain_ts), 4),
                digest_s=round(min(digest_ts), 4),
                total_s=round(total, 4),
                ops_per_sec=round(total_ops / max(total, 1e-9), 1))


def measure_fused_pipeline(docs, rounds, opd, slots=384, marks=96):
    """Fused-pipeline decomposition (ISSUE 9 satellite): how much of the
    host's parse/schedule wall the pipelined drain actually HIDES behind
    device compute.

    Three honest measurements over the same live workload:

    * ``pipelined_s`` — the fused discipline end-to-end (pipelined drain:
      staged batches, async dispatch, staging lane);
    * ``serialized_s`` — the identical session forced lock-step: a device
      sync after every drain, so host work and device math strictly
      alternate (the no-overlap upper bound);
    * ``host_parse_s`` — the session's own wire-parse wall
      (``host_parse_seconds``).

    ``overlap_hidden_s = serialized_s - pipelined_s`` is the wall the
    pipeline removed; ``parse_overlap_ratio = clamp(hidden / host_parse,
    0, 1)`` expresses it against the parse stage the ISSUE attributes the
    streaming gap to — the remaining-gap attribution the fused row's
    throughput alone cannot give."""
    import time as _time

    from bench import build_arrival
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=0, num_docs=docs, ops_per_doc=opd)
    arrival, _ = build_arrival(workloads, rounds, 0)
    total_ops = sum(len(ch.ops) for w in workloads for log in w.values()
                    for ch in log)

    def run(serialize: bool):
        s = StreamingMerge(
            num_docs=docs, actors=("doc1", "doc2", "doc3"),
            slot_capacity=slots, mark_capacity=marks, tomb_capacity=slots,
            round_insert_capacity=64, round_delete_capacity=32,
            round_mark_capacity=32, round_map_capacity=16,
        )
        t0 = _time.perf_counter()
        for r in range(rounds):
            s.ingest_frames(
                (doc, b[r]) for doc, b in enumerate(arrival) if r < len(b))
            s.drain()
            if serialize:
                s.sync_device()
        digest = s.digest()
        return _time.perf_counter() - t0, digest, s

    run(False)  # warm compiles
    run(True)
    pipe, dg_a, s_pipe = min(
        (run(False) for _ in range(3)), key=lambda x: x[0])
    serial, dg_b, _ = min((run(True) for _ in range(3)), key=lambda x: x[0])
    assert dg_a == dg_b, "overlap must not change the digest"
    hidden = max(0.0, serial - pipe)
    parse = max(s_pipe.host_parse_seconds, 1e-9)
    row = dict(
        docs=docs, rounds=rounds, staged_rounds=s_pipe.rounds,
        ops=total_ops, mode="fused",
        pipelined_s=round(pipe, 4),
        serialized_s=round(serial, 4),
        host_parse_s=round(s_pipe.host_parse_seconds, 4),
        overlap_hidden_s=round(hidden, 4),
        parse_overlap_ratio=round(min(1.0, hidden / parse), 3),
        ops_per_sec=round(total_ops / pipe, 1),
    )
    print(f"fused pipeline: pipelined {pipe*1e3:7.1f} ms  "
          f"serialized {serial*1e3:7.1f} ms  "
          f"parse {s_pipe.host_parse_seconds*1e3:6.1f} ms  "
          f"hidden {hidden*1e3:6.1f} ms  "
          f"overlap_ratio {row['parse_overlap_ratio']}")
    return row


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--fine", action="store_true",
                        help="honest-sync launch/compute/sync decomposition "
                        "(the old engine_profile2.py)")
    parser.add_argument("--sweep", action="store_true",
                        help="sweep round depth x docs (coarse mode only)")
    parser.add_argument("--profile", action="store_true",
                        help="capture a jax.profiler trace to /tmp/engine_trace")
    parser.add_argument("--docs", type=int, default=2048)
    parser.add_argument("--rounds", type=int, default=4)
    parser.add_argument("--ops-per-doc", type=int, default=192)
    parser.add_argument("--slots", type=int, default=384)
    parser.add_argument("--marks", type=int, default=96)
    parser.add_argument("--devprof-out", default=None, metavar="PATH",
                        help="write the devprof snapshot (shape buckets, "
                        "occupancy, memory watermarks) as JSON to PATH — the "
                        "same schema the perf ledger stores")
    parser.add_argument("--ledger", default=None, metavar="PATH",
                        help="append a perf-ledger record (throughput row + "
                        "devprof snapshot) to PATH")
    args = parser.parse_args(argv)

    from peritext_tpu.obs import GLOBAL_DEVPROF

    GLOBAL_DEVPROF.enable(capture_costs=True)

    if args.fine:
        results = [measure_fine(args.docs, args.rounds, args.ops_per_doc,
                                args.slots, args.marks),
                   measure_fused_pipeline(args.docs, args.rounds,
                                          args.ops_per_doc, args.slots,
                                          args.marks)]
    else:
        shapes = [(args.docs, args.rounds, args.ops_per_doc)]
        if args.sweep:
            shapes = [
                (2048, 4, 192),   # the bench shape
                (2048, 1, 192),   # one big round: all ops in a single apply
                (2048, 2, 192),
                (2048, 8, 192),
                (2048, 16, 192),
                (512, 4, 192),
                (8192, 4, 192),
            ]
        results = []
        for docs, rounds, opd in shapes:
            r = measure(docs, rounds, opd, args.slots, args.marks,
                        profile_dir="/tmp/engine_trace" if args.profile else None)
            print(r)
            results.append(r)

    if args.devprof_out:
        with open(args.devprof_out, "w") as fh:
            json.dump(GLOBAL_DEVPROF.snapshot(), fh, indent=1)
        print(f"devprof snapshot -> {args.devprof_out}")
    if args.ledger:
        from peritext_tpu.obs import ledger as _ledger

        # fine mode measures a two-sync pass (chain + digest separately),
        # coarse mode a single-sync pass — distinct row identities so the
        # two never pollute each other's rolling reference
        rows = [
            dict(row=({"fine": "engine_profile_fine",
                       "fused": "fused_pipeline"}.get(r.get("mode"),
                                                      "engine_profile"))
                 + f"[{r['docs']}x{r['staged_rounds']}]",
                 metric="engine_profile_ops_per_sec", value=r["ops_per_sec"],
                 unit="ops/s", docs=r["docs"], rounds=r["rounds"])
            for r in results
        ]
        _ledger.append_record(args.ledger, _ledger.ledger_record(
            rows, config="engine_profile",
            devprof=GLOBAL_DEVPROF.snapshot(),
        ))
        print(f"perf-ledger record -> {args.ledger}")


if __name__ == "__main__":
    main()
