#!/usr/bin/env python
"""Multi-chip weak-scaling evidence on the virtual device mesh.

Real multi-chip hardware is not reachable from this harness (one tunneled
TPU chip), so this is the next-best artifact, per SURVEY §4's "multi-node
without a cluster" recipe: N virtual CPU devices
(``--xla_force_host_platform_device_count``), a ``jax.sharding.Mesh`` over
the doc axis, and the SAME merge programs the TPU path runs.

For each mesh size 1/2/4/8 it measures, at FIXED docs-per-device (weak
scaling):

* batch merge wall time + per-device throughput (DocBatch over the mesh),
* streaming merge wall time + per-device throughput (StreamingMerge rounds),
* the convergence digest of a FIXED 16-doc probe workload, which must be
  IDENTICAL across every mesh size (re-sharding must never change content).

Emits one JSON line per mesh size plus a final summary line; the BASELINE.md
weak-scaling table is generated from this output.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--docs-per-device", type=int, default=64)
    parser.add_argument("--ops-per-doc", type=int, default=96)
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--sizes", type=int, nargs="+", default=[1, 2, 4, 8])
    args = parser.parse_args()

    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from peritext_tpu.utils.platform import pin_cpu_platform

    devices = pin_cpu_platform(max(args.sizes))

    import numpy as np
    import jax
    from jax.sharding import Mesh

    from peritext_tpu.api.batch import DocBatch
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    probe = generate_workload(args.seed ^ 0xD16, num_docs=16, ops_per_doc=48)
    digests = {}

    for n in args.sizes:
        mesh = Mesh(np.asarray(devices[:n]), ("docs",))
        docs = args.docs_per_device * n
        workloads = generate_workload(args.seed, num_docs=docs,
                                      ops_per_doc=args.ops_per_doc)
        total_ops = sum(
            len(ch.ops) for w in workloads for log in w.values() for ch in log
        )

        # ---- batch merge over the mesh ----
        batch = DocBatch(slot_capacity=4 * args.ops_per_doc,
                         mark_capacity=2 * args.ops_per_doc,
                         comment_capacity=32, mesh=mesh)
        batch.merge(workloads)  # warm: compiles are per (docs, caps) shape
        t0 = time.perf_counter()
        report = batch.merge(workloads)
        batch_s = time.perf_counter() - t0
        assert not report.fallback_docs, report.fallback_docs

        # ---- streaming merge over the mesh ----
        def mk():
            return StreamingMerge(
                num_docs=docs, actors=("doc1", "doc2", "doc3"), mesh=mesh,
                slot_capacity=4 * args.ops_per_doc,
                mark_capacity=2 * args.ops_per_doc,
                tomb_capacity=2 * args.ops_per_doc,
                round_insert_capacity=128, round_delete_capacity=64,
                round_mark_capacity=64,
            )

        frames = [
            encode_frame([ch for log in w.values() for ch in log])
            for w in workloads
        ]
        s = mk()  # warm
        s.ingest_frames(list(enumerate(frames)))
        s.drain()
        s.digest()
        t0 = time.perf_counter()
        s = mk()
        s.ingest_frames(list(enumerate(frames)))
        t_ingest = time.perf_counter() - t0
        s.drain()
        # drain() only ENQUEUES the jitted apply programs (step() documents
        # the async dispatch); without an explicit sync the apply compute
        # would be mis-attributed to the digest stage below
        np.asarray(s.state.num_slots)
        t_drain = time.perf_counter() - t0 - t_ingest
        s.digest()
        t_digest = time.perf_counter() - t0 - t_ingest - t_drain
        stream_s = t_ingest + t_drain + t_digest

        # ---- sharding-overhead probe: SAME total work on every mesh size —
        # with docs fixed, any slowdown vs mesh=1 is genuine sharding/
        # collective overhead, while the weak-scaling totals above also
        # absorb shared-CPU contention (all virtual devices share one chip)
        fixed_docs = args.docs_per_device
        fixed_w = generate_workload(args.seed ^ 0xF1, num_docs=fixed_docs,
                                    ops_per_doc=args.ops_per_doc)
        fixed_frames = [
            encode_frame([ch for log in w.values() for ch in log])
            for w in fixed_w
        ]
        fixed_ops = sum(
            len(ch.ops) for w in fixed_w for log in w.values() for ch in log
        )

        def fixed_run():
            fs = StreamingMerge(
                num_docs=fixed_docs, actors=("doc1", "doc2", "doc3"), mesh=mesh,
                slot_capacity=4 * args.ops_per_doc,
                mark_capacity=2 * args.ops_per_doc,
                tomb_capacity=2 * args.ops_per_doc,
                round_insert_capacity=128, round_delete_capacity=64,
                round_mark_capacity=64,
            )
            fs.ingest_frames(list(enumerate(fixed_frames)))
            fs.drain()
            fs.digest()

        fixed_run()  # warm
        t0 = time.perf_counter()
        fixed_run()
        fixed_s = time.perf_counter() - t0

        # ---- touched-round digest (r3 VERDICT task 2 "done" signal) ----
        # a converged session absorbs a FIXED 16-doc round (the held-back
        # second half of those docs' real histories, so causality holds);
        # the incremental digest must re-resolve only the touched span, so
        # this stage must NOT grow with the session's total docs (idle
        # rounds are cheaper still: all carried).  Mesh sessions hold one
        # whole-batch block, so their touched span is docs/devices — flat
        # per device under weak scaling.
        warm_round, held = {}, {}
        first_frames = []
        for i, w in enumerate(workloads):
            ch = [c for log in w.values() for c in log]
            if i < 16:
                first_frames.append(encode_frame(ch[: len(ch) // 3]))
                warm_round[i] = encode_frame(ch[len(ch) // 3: 2 * len(ch) // 3])
                held[i] = encode_frame(ch[2 * len(ch) // 3:])
            else:
                first_frames.append(encode_frame(ch))
        ts = mk()
        ts.ingest_frames(list(enumerate(first_frames)))
        ts.drain()
        ts.digest()  # warm the carried row plane
        ts.ingest_frames(list(warm_round.items()))
        ts.drain()
        ts.digest()  # warm the touched-rows sub-batch program (compiles)
        ts.ingest_frames(list(held.items()))
        ts.drain()
        np.asarray(ts.state.num_slots)  # attribute apply to its own stage
        t0 = time.perf_counter()
        ts.digest()
        touched_digest_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        ts.digest()
        idle_digest_s = time.perf_counter() - t0

        # shard-count sanity: the doc axis really spans all n devices
        n_shards = len(s.state.elem_id.sharding.device_set)
        assert n_shards == n, f"expected {n} shards, got {n_shards}"

        # ---- skewed arrival + reshard (SURVEY §5.8(c)) ----
        # first-seen placement pins heavy docs wherever they arrived; the
        # reshard all-to-all must restore per-shard load balance with the
        # digest bit-unchanged
        skew_stats = None
        if n > 1:
            sk_docs = args.docs_per_device * n
            heavy = generate_workload(args.seed ^ 0x5E, num_docs=sk_docs // 4,
                                      ops_per_doc=args.ops_per_doc * 3)
            light = generate_workload(args.seed ^ 0x5F, num_docs=sk_docs - len(heavy),
                                      ops_per_doc=max(8, args.ops_per_doc // 4))
            sk_w = heavy + light  # heavy docs all land in the first shard(s)
            sk = StreamingMerge(
                num_docs=sk_docs, actors=("doc1", "doc2", "doc3"), mesh=mesh,
                slot_capacity=12 * args.ops_per_doc,
                mark_capacity=6 * args.ops_per_doc,
                tomb_capacity=6 * args.ops_per_doc,
                round_insert_capacity=256, round_delete_capacity=128,
                round_mark_capacity=128,
            )
            sk.ingest_frames(
                (d, encode_frame([ch for log in w.values() for ch in log]))
                for d, w in enumerate(sk_w)
            )
            sk.drain()
            d_before = sk.digest()

            def shard_loads(sess):
                slots = np.asarray(sess.state.num_slots)
                per = sess._padded_docs // n
                return [int(slots[i * per:(i + 1) * per].sum()) for i in range(n)]

            loads_before = shard_loads(sk)
            t0 = time.perf_counter()
            moved = sk.reshard()
            np.asarray(sk.state.num_slots)  # sync the gather
            reshard_s = time.perf_counter() - t0
            loads_after = shard_loads(sk)
            assert sk.digest() == d_before, "reshard changed the digest"
            skew_stats = {
                "docs": sk_docs,
                "moved_docs": moved["moved"],
                "reshard_seconds": round(reshard_s, 3),
                "shard_load_before": loads_before,
                "shard_load_after": loads_after,
                "imbalance_before": round(max(loads_before) / max(1, min(loads_before)), 2),
                "imbalance_after": round(max(loads_after) / max(1, min(loads_after)), 2),
            }

        # ---- fixed-probe digest: content must be mesh-size invariant ----
        ps = StreamingMerge(
            num_docs=16, actors=("doc1", "doc2", "doc3"), mesh=mesh,
            slot_capacity=256, mark_capacity=128, tomb_capacity=128,
        )
        for d, w in enumerate(probe):
            ps.ingest(d, [ch for log in w.values() for ch in log])
        ps.drain()
        digests[n] = ps.digest()

        print(json.dumps({
            "mesh_devices": n,
            "docs": docs,
            "total_ops": total_ops,
            "batch_seconds": round(batch_s, 3),
            "batch_ops_per_sec_total": round(total_ops / batch_s, 1),
            "batch_ops_per_sec_per_device": round(total_ops / batch_s / n, 1),
            "streaming_seconds": round(stream_s, 3),
            "streaming_ops_per_sec_total": round(total_ops / stream_s, 1),
            "streaming_ops_per_sec_per_device": round(total_ops / stream_s / n, 1),
            "streaming_stage_seconds": {
                "ingest_host": round(t_ingest, 3),
                "schedule_apply": round(t_drain, 3),
                "digest": round(t_digest, 3),
            },
            "fixed_work_seconds": round(fixed_s, 3),
            "fixed_work_ops_per_sec": round(fixed_ops / fixed_s, 1),
            "touched_round_digest_seconds": round(touched_digest_s, 3),
            "idle_round_digest_seconds": round(idle_digest_s, 4),
            "skewed_arrival_reshard": skew_stats,
            "probe_digest": digests[n],
        }))

    assert len(set(digests.values())) == 1, f"digest mismatch across meshes: {digests}"
    print(json.dumps({
        "summary": "weak-scaling",
        "sizes": args.sizes,
        "digest_equal_across_mesh_sizes": True,
        "probe_digest": digests[args.sizes[0]],
    }))


if __name__ == "__main__":
    main()
