#!/usr/bin/env python
"""obs smoke: a 128-doc CPU streaming session with tracing on.

The CI contract (and ``make obs`` locally): run a real streaming merge with
the tracer enabled, assert that a NON-EMPTY Perfetto dump parses back as
Chrome trace-event JSON covering every pipeline stage, write the artifacts
(``trace.json``, ``health.json``) for upload, and print the per-stage
summary table.  Exit nonzero on any violation — an observability regression
fails CI like a correctness one.
"""

import argparse
import json
import os
import random
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: stages the dump must cover: the streaming pipeline plus digest
REQUIRED_STAGES = (
    "streaming.ingest", "streaming.schedule", "streaming.apply",
    "streaming.resolve", "streaming.decode", "streaming.patch-scatter",
    "streaming.digest",
)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--docs", type=int, default=128)
    parser.add_argument("--ops-per-doc", type=int, default=24)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--out", default="obs-artifacts",
                        help="artifact directory (trace.json, health.json)")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from peritext_tpu.obs import Tracer, health_snapshot
    from peritext_tpu.obs.__main__ import load_spans, render_table, summarize
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.fuzz import _campaign_session, generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    tracer = Tracer(host="obs-smoke", enabled=True)
    session = _campaign_session(args.docs, args.ops_per_doc)
    session.tracer = tracer

    rng = random.Random(args.seed)
    workloads = generate_workload(
        args.seed, num_docs=args.docs, ops_per_doc=args.ops_per_doc
    )
    for d, workload in enumerate(workloads):
        changes = [ch for log in workload.values() for ch in log]
        rng.shuffle(changes)
        frames = [encode_frame(changes[i:i + 9])
                  for i in range(0, len(changes), 9)]
        session.ingest_frames((d, f) for f in frames)
        if d % 16 == 0:
            session.step()
    session.drain()
    session.read_all()
    session.read_patches_all()
    digest = session.digest()

    trace_path = out / "trace.json"
    tracer.write_chrome_trace(trace_path)
    (out / "health.json").write_text(
        json.dumps(health_snapshot(session=session), indent=2, default=str)
    )

    # -- the smoke assertions -------------------------------------------------
    doc = json.loads(trace_path.read_text())  # must parse back
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    if not events:
        print("obs-smoke FAIL: Perfetto dump has no span events", file=sys.stderr)
        return 1
    bad = [e for e in events
           if not all(k in e for k in ("name", "ts", "dur", "pid", "tid"))]
    if bad:
        print(f"obs-smoke FAIL: malformed events: {bad[:3]}", file=sys.stderr)
        return 1
    names = {e["name"] for e in events}
    missing = [s for s in REQUIRED_STAGES if s not in names]
    if missing:
        print(f"obs-smoke FAIL: stages missing from trace: {missing}",
              file=sys.stderr)
        return 1

    print(f"obs-smoke OK: {len(events)} spans, digest={digest:#010x}, "
          f"artifacts in {out}/")
    print(render_table(summarize(load_spans(trace_path))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
