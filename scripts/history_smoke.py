#!/usr/bin/env python
"""history smoke: the fleet history plane end to end on CPU.

The CI contract (and ``make history-smoke`` locally): drive a REAL armed
serve session and assert the plane retains frames, rolls JSONL segments
over, cascades the retention tiers, and replays the persisted segments
back into a byte-identical ring; run the serve-overload chaos episode and
assert the injected fault scores as an anomaly no later than the round
its incident opens; exercise the ``obs history`` exit contract (0 clean /
1 active anomaly / 2 unreadable) and the history-weighted ``obs plan``
replay (same occupancy history -> byte-identical proposal, and a proposal
that DIFFERS from the snapshot-only one on a bimodal fixture); and pin
the arming cost: sampling over steady-state serve rounds compiles ZERO
XLA programs and a synthetic feed stays wall-clock cheap.  Artifacts
(``history.json``, ``history.prom``, ``serve_chaos.json``, ``plan.json``,
``segments/``) land in ``--out`` for upload.  Exit nonzero on any
violation — an observability regression fails CI like a correctness one.
"""

import argparse
import io
import json
import os
import sys
import time
from contextlib import redirect_stdout
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: feeding budget: 2k advance_round samples of a busy plane must stay
#: under this wall — retention is dict folds, not device work
FEED_ROUNDS = 2000
FEED_BUDGET_S = 2.0

#: bimodal occupancy fixture: p90 lands on the dense mode, flipping the
#: planner's width-shrink gate vs the snapshot-only point estimate
BIMODAL = [0.05] * 12 + [0.9] * 4


def fail(msg: str) -> int:
    print(f"history-smoke FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=3)
    parser.add_argument("--out", default="history-artifacts")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from peritext_tpu.obs import (
        RecompileSentinel,
        TimeSeriesPlane,
        prometheus_text,
        replay_segments,
    )
    from peritext_tpu.obs.__main__ import main as obs_main
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.plan import propose
    from peritext_tpu.serve import SessionMux
    from peritext_tpu.testing.chaos import run_serve_chaos
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    seg_dir = out / "segments"

    # -- a REAL armed serve session: retention + segments + zero compiles ----
    def make_mux():
        return SessionMux(
            StreamingMerge(
                num_docs=1, actors=("doc1", "doc2", "doc3"),
                slot_capacity=256, mark_capacity=64, tomb_capacity=128,
                round_insert_capacity=32, round_delete_capacity=16,
                round_mark_capacity=16, static_rounds=True,
            ),
            host="smoke",
        )

    def drive(mux, plane=None):
        sid, verdict = mux.open_session("client")
        assert verdict.admitted
        if plane is not None:
            mux.history_plane = plane
        for frame in frames:
            assert mux.submit(sid, frame).admitted
            mux.flush()

    w = generate_workload(seed=args.seed, num_docs=1, ops_per_doc=80)[0]
    changes = sorted((ch for log in w.values() for ch in log),
                     key=lambda c: (c.actor, c.seq))
    frames = [encode_frame(changes[i::40]) for i in range(40)]
    t0 = time.perf_counter()
    drive(make_mux())  # cold: every staged variant compiles OUTSIDE arming
    plane = TimeSeriesPlane(sample_every=1, tier_capacity=8, merge_factor=2,
                            tiers=3, min_frames=4, segment_frames=16,
                            dir=seg_dir, host="smoke").enable()
    with RecompileSentinel() as sentinel:
        sentinel.mark()
        t_armed = time.perf_counter()
        drive(make_mux(), plane=plane)
        plane.note_overhead(time.perf_counter() - t_armed)
        try:
            sentinel.assert_steady_state(
                "armed history sampling over steady-state serve rounds")
        except AssertionError as exc:
            return fail(f"arming compiled XLA programs: {exc}")
    serve_s = time.perf_counter() - t0
    snap = plane.snapshot()
    if plane.frames_sampled < len(frames):
        return fail(f"armed session retained {plane.frames_sampled} frames, "
                    f"want >= {len(frames)}")
    if plane.segments() < 2:
        return fail(f"{plane.frames_sampled} frames never rolled a segment "
                    f"over (segments={plane.segments()})")
    if sum(1 for n in snap["tier_frames"][1:] if n) == 0:
        return fail(f"retention never cascaded past tier 0: "
                    f"{snap['tier_frames']}")
    replayed = replay_segments(seg_dir, tier_capacity=8, merge_factor=2,
                               tiers=3, host="smoke")
    if replayed.frames_json() != plane.frames_json():
        return fail("segment replay did not reconstruct the ring "
                    "byte-identically")
    print(f"history-smoke: armed serve session OK in {serve_s:.1f}s "
          f"({plane.frames_sampled} frames, {plane.segments()} segments, "
          f"tiers {snap['tier_frames']}, replay byte-identical, 0 compiles)")

    # -- the chaos oracle: injected overload scores as an anomaly ------------
    t0 = time.perf_counter()
    report = run_serve_chaos(args.seed, hosts=3)
    chaos_s = time.perf_counter() - t0
    (out / "serve_chaos.json").write_text(
        json.dumps(report.to_json(), indent=2))
    if not report.anomaly_keys:
        return fail("serve chaos flagged no anomaly keys")
    if any(not k.startswith("serve.") for k in report.anomaly_keys):
        return fail(f"anomaly keys off the serve plane: {report.anomaly_keys}")
    if report.anomaly_detection_rounds < 0:
        return fail("anomaly detection round missing from the episode report")
    print(f"history-smoke: serve-chaos episode OK in {chaos_s:.1f}s "
          f"(anomalies {report.anomaly_keys} after "
          f"{report.anomaly_detection_rounds} round(s))")

    # -- the obs history exit contract ---------------------------------------
    quiet = TimeSeriesPlane(min_frames=4).enable()
    for i in range(8):
        quiet.sample(serve={"admitted": float(i * 2), "depth": 1.0})
    spiked = TimeSeriesPlane(min_frames=4).enable()
    for _ in range(6):
        spiked.sample(serve={"shed": 0.0})
    spiked.sample(serve={"shed": 50.0})
    clean_dir = out / "clean"
    hot_dir = out / "hot"
    clean_dir.mkdir(exist_ok=True)
    hot_dir.mkdir(exist_ok=True)
    (clean_dir / "timeseries.json").write_text(
        json.dumps(quiet.snapshot(), default=str))
    (hot_dir / "timeseries.json").write_text(
        json.dumps(spiked.snapshot(), default=str))
    (out / "history.json").write_text(json.dumps(snap, default=str))
    rc = obs_main(["history", str(clean_dir)])
    if rc != 0:
        return fail(f"obs history exit {rc} on a clean snapshot (want 0)")
    rc = obs_main(["history", str(clean_dir), "--key", "serve.admitted",
                   "--rate"])
    if rc != 0:
        return fail(f"obs history --key exit {rc} on a clean gauge (want 0)")
    rc = obs_main(["history", str(hot_dir)])
    if rc != 1:
        return fail(f"obs history exit {rc} with an active anomaly (want 1)")
    rc = obs_main(["history", str(out / "missing")])
    if rc != 2:
        return fail(f"obs history exit {rc} on unreadable input (want 2)")

    # -- the history-weighted planner replay ---------------------------------
    devprof_path = Path(__file__).resolve().parents[1] / "perf" \
        / "plan_devprof.json"
    devprof = json.loads(devprof_path.read_text())
    base = propose(devprof)
    weighted = propose(devprof, history=BIMODAL)
    again = propose(devprof, history=list(BIMODAL))
    if (json.dumps(weighted.to_json(), sort_keys=True)
            != json.dumps(again.to_json(), sort_keys=True)):
        return fail("same occupancy history produced two different proposals")
    if weighted.to_json() == base.to_json():
        return fail("bimodal occupancy history did not move the proposal")
    if "history" not in weighted.modeled:
        return fail("history-weighted proposal lacks the modeled history "
                    "block")
    (out / "plan.json").write_text(json.dumps(weighted.to_json(), indent=2))
    hist_plane = TimeSeriesPlane(min_frames=4).enable()
    for occ in BIMODAL:
        hist_plane.record_occupancy(0, occ)
    hist_path = out / "occupancy.json"
    hist_path.write_text(json.dumps(hist_plane.snapshot(), default=str))
    renders = []
    for _ in range(2):
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = obs_main(["plan", str(devprof_path), "--history",
                           str(hist_path), "--json"])
        if rc not in (0, 1):
            return fail(f"obs plan --history exit {rc} (want 0 or 1)")
        renders.append(buf.getvalue())
    if renders[0] != renders[1]:
        return fail("obs plan --history replay was not deterministic")
    if '"weighted_terms"' not in renders[0]:
        return fail("obs plan --history omitted the history-weighted terms")
    print("history-smoke: planner replay OK (history-weighted proposal "
          f"deviates from snapshot-only: insert_width {base.insert_width} "
          f"-> {weighted.insert_width}, byte-stable across replays)")

    # -- gauges --------------------------------------------------------------
    text = prometheus_text(history=plane)
    (out / "history.prom").write_text(text)
    for needle in ("peritext_history_frames_retained ",
                   "peritext_history_segments ",
                   'peritext_history_tier_frames{tier="0"}',
                   "peritext_build_info{"):
        if needle not in text:
            return fail(f"{needle!r} missing from the exposition")

    # -- feeding cost: zero compiles, cheap wall -----------------------------
    with RecompileSentinel() as sentinel:
        before = sentinel.total
        feed = TimeSeriesPlane(sample_every=4, min_frames=8).enable()
        t0 = time.perf_counter()
        for n in range(FEED_ROUNDS):
            feed.advance_round(serve={"depth": n % 5, "admitted": n},
                               fleet={"hosts": 3, "dead": 0})
        wall = time.perf_counter() - t0
        feed.note_overhead(wall)
        if sentinel.total != before:
            return fail("feeding the history plane dispatched XLA compiles")
    if wall > FEED_BUDGET_S:
        return fail(f"{FEED_ROUNDS} sampled rounds took {wall:.2f}s "
                    f"(budget {FEED_BUDGET_S}s)")

    print(f"history-smoke OK: {plane.frames_sampled} serve frames across "
          f"{plane.segments()} segment(s), {FEED_ROUNDS} synthetic rounds in "
          f"{wall * 1e3:.0f}ms, 0 compiles, artifacts in {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
