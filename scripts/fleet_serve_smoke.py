#!/usr/bin/env python
"""fleet-serve smoke: the live-fleet failover CI contract (and
``make fleet-serve-smoke``).

Runs the ISSUE-10 acceptance episode end to end on CPU: a 3-host
:class:`FleetFrontend` (real TCP ship endpoints) carries round-robin
client traffic, one serving host is KILLED mid-traffic, the deterministic
round-counted heartbeat lease detects it, and failover re-homes the dead
host's docs from the last shipped checkpoint + journal redelivery.
Asserted promises (inside ``testing/chaos.run_host_kill_failover``):

* **typed verdicts only** — zero silent drops across the kill window; the
  fleet-wide accounting identity holds and every shed reason is typed;
* **acked-op survival** — every admitted frame is reflected in the
  re-homed docs' state before any client retry;
* **post-heal byte equality** — after retries drain, every doc (and the
  fleet-wide digest sum) equals a fault-free reference run bit-for-bit;
* **observable** — the failover timeline lands in flight-recorder dumps,
  and a second, live frontend episode is scraped through ``/fleet.json``
  + the ``peritext_fleet_*`` gauges to pin the exporter surface.

Artifacts (``fleet-serve-report.json``, ``fleet.json`` snapshot, flight
dumps) are written for upload.  Exit nonzero on any violation — a
failover regression fails CI like a correctness one.
"""

import argparse
import json
import os
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--out", default="fleet-serve-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    from peritext_tpu.obs import MetricsServer, prometheus_text
    from peritext_tpu.serve import (
        AdmissionController, FleetFrontend, SessionMux,
    )
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.testing.chaos import (
        _serve_session, run_host_kill_failover,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    dump_dir = out / "flight"
    dump_dir.mkdir(exist_ok=True)

    # -- the acceptance episode (all oracles assert inside) -----------------
    report = run_host_kill_failover(
        args.seed, hosts=3, num_docs=6, ops_per_doc=24,
        transport=True, dump_dir=dump_dir,
    )
    assert report.acked_survived and report.converged, report.to_json()
    assert report.delayed + report.shed > 0, (
        "the kill produced no typed-verdict evidence"
    )
    print(
        f"host-kill episode: victim={report.victim} "
        f"({report.victim_docs} docs), detection in "
        f"{report.detection_rounds} rounds, {report.failover_docs} docs "
        f"re-homed, {report.offered} offered = {report.admitted} admitted "
        f"+ {report.delayed} delayed + {report.shed} shed"
    )

    # -- exporter surface on a live frontend --------------------------------
    fe = FleetFrontend(lease_rounds=2, checkpoint_every=2)
    for i in range(3):
        fe.add_host(f"host{i}", SessionMux(
            _serve_session(4, 24),
            admission=AdmissionController(max_depth=64, session_quota=None),
        ))
    try:
        workloads = generate_workload(args.seed + 1, num_docs=3,
                                      ops_per_doc=24)
        for d, w in enumerate(workloads):
            changes = [ch for log in sorted(w) for ch in w[log]]
            assert fe.open_doc(f"doc{d}", f"client{d}").admitted
            for i in range(0, len(changes), 6):
                assert fe.submit(
                    f"doc{d}", encode_frame(changes[i:i + 6])).admitted
        fe.round()
        fe.flush()
        fe.hosts["host1"].kill()
        for _ in range(3):
            fe.round()
        assert fe.failovers == 1, "exporter episode failover missing"

        server = MetricsServer(fleet=fe)
        host, port = server.start()
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/fleet.json", timeout=5
            ).read())
        finally:
            server.stop()
        assert body["failovers"] == 1
        assert body["leases"]["leases"]["host1"]["verdict"] == "dead"
        (out / "fleet.json").write_text(json.dumps(body, indent=2))

        text = prometheus_text(fleet=fe)
        for needle in ("peritext_fleet_dead_hosts 1",
                       "peritext_fleet_failovers_total 1"):
            assert needle in text, needle
    finally:
        fe.stop()

    dumps = sorted(dump_dir.glob("*.jsonl"))
    assert dumps, "no flight-recorder failover timeline dumped"
    (out / "fleet-serve-report.json").write_text(
        json.dumps(report.to_json(), indent=2)
    )
    print(f"fleet-serve smoke OK; artifacts in {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
