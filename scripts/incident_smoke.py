#!/usr/bin/env python
"""incident smoke: the fleet incident plane end to end on CPU.

The CI contract (and ``make incident-smoke`` locally): run the host-kill
chaos episode with a private incident monitor riding the fleet snapshot
and assert it opens EXACTLY a host-death incident, resolves it post-heal,
and reports time-to-detection in monitor rounds; merge the episode's
flight dumps into the cross-host black-box timeline; exercise the
``obs incidents`` / ``obs status`` / ``obs flight`` exit contracts
(0 clean / 1 open or unhealthy / 2 unreadable); and pin the arming cost:
feeding the plane compiles ZERO XLA programs and stays wall-clock cheap.
Artifacts (``hostkill.json``, ``incidents.json``, ``incidents.prom``,
``timeline.json``) land in ``--out`` for upload.  Exit nonzero on any
violation — an observability regression fails CI like a correctness one.
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: feeding budget: 2k observe+advance rounds of a busy monitor must stay
#: under this wall — the plane is dict folds, not device work
FEED_ROUNDS = 2000
FEED_BUDGET_S = 2.0


def fail(msg: str) -> int:
    print(f"incident-smoke FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=2)
    parser.add_argument("--out", default="incident-artifacts")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from peritext_tpu.obs import IncidentMonitor, merge_flight_dumps
    from peritext_tpu.obs.__main__ import main as obs_main
    from peritext_tpu.obs.exporters import prometheus_text
    from peritext_tpu.obs.sentinel import RecompileSentinel
    from peritext_tpu.testing.chaos import run_host_kill_failover

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    flight_dir = out / "flight"
    flight_dir.mkdir(exist_ok=True)

    # -- the chaos oracle: host-kill opens EXACTLY host-death ----------------
    t0 = time.perf_counter()
    report = run_host_kill_failover(
        args.seed, hosts=3, num_docs=4, ops_per_doc=16, transport=False,
        dump_dir=flight_dir,
    )
    episode_s = time.perf_counter() - t0
    (out / "hostkill.json").write_text(json.dumps(report.to_json(), indent=2))
    if report.incident_kinds != ["host-death"]:
        return fail(f"host-kill opened {report.incident_kinds}, "
                    "expected exactly ['host-death']")
    if not report.incident_resolved:
        return fail("host-death incident never resolved post-heal")
    if report.incident_detection_rounds < 1:
        return fail("time-to-detection missing from the episode report")
    print(f"incident-smoke: host-kill episode OK in {episode_s:.1f}s "
          f"(victim={report.victim}, "
          f"detection={report.incident_detection_rounds} monitor rounds)")

    # -- the merged black-box timeline ---------------------------------------
    merged = merge_flight_dumps(flight_dir.glob("flight-*.jsonl"))
    (out / "timeline.json").write_text(json.dumps(merged, indent=2,
                                                  default=str))
    if not merged["records"]:
        return fail("the episode's flight dumps merged to an empty timeline")
    if "?" in merged["hosts"]:
        return fail("a flight dump lost its host attribution")
    reasons = {d["reason"] for d in merged["dumps"]}
    if "host-death" not in reasons:
        return fail(f"merged timeline lacks the host-death dump: {reasons}")
    rc = obs_main(["flight", str(flight_dir)])
    if rc != 0:
        return fail(f"obs flight exit {rc} on a dump dir (want 0)")

    # -- the CLI exit contracts ----------------------------------------------
    def synth_monitor(open_incident: bool) -> IncidentMonitor:
        m = IncidentMonitor(host="smoke")
        if open_incident:
            m.raise_signal("shed-storm", host="h0", value=5)
            m.raise_signal("slo-burn", host="h0", value=2)
        m.advance_round()
        return m

    open_m, clean_m = synth_monitor(True), synth_monitor(False)
    snap_dir = out / "status"
    snap_dir.mkdir(exist_ok=True)
    (out / "incidents.json").write_text(json.dumps(open_m.snapshot()))
    (snap_dir / "incidents.json").write_text(json.dumps(clean_m.snapshot()))
    rc = obs_main(["incidents", str(out / "incidents.json")])
    if rc != 1:
        return fail(f"obs incidents exit {rc} with an open incident (want 1)")
    rc = obs_main(["incidents", str(snap_dir / "incidents.json")])
    if rc != 0:
        return fail(f"obs incidents exit {rc} on a clean snapshot (want 0)")
    rc = obs_main(["incidents", str(out / "missing.json")])
    if rc != 2:
        return fail(f"obs incidents exit {rc} on unreadable input (want 2)")
    rc = obs_main(["status", str(snap_dir)])
    if rc != 0:
        return fail(f"obs status exit {rc} on a clean snapshot dir (want 0)")

    # correlated view: the two same-host signals collapsed into ONE
    # incident with the larger delta as root cause
    snap = open_m.snapshot()
    if snap["total"] != 1 or snap["incidents"][0]["kind"] != "shed-storm":
        return fail(f"correlation broke: {snap['incidents']}")

    # -- gauges --------------------------------------------------------------
    text = prometheus_text(incidents=open_m)
    (out / "incidents.prom").write_text(text)
    for needle in ("peritext_incident_open ", "peritext_build_info{",
                   'peritext_incident_open_by_kind{kind="host-death"}'):
        if needle not in text:
            return fail(f"{needle!r} missing from the exposition")

    # -- arming cost: zero compiles, cheap wall ------------------------------
    with RecompileSentinel() as sentinel:
        before = sentinel.total
        m = IncidentMonitor(host="smoke")
        t0 = time.perf_counter()
        for n in range(FEED_ROUNDS):
            if n % 7 == 0:
                m.observe_serve({"host": "h0", "recent_sheds": n % 3,
                                 "overloaded": False})
            m.observe_sentinel({"total": 0})
            m.advance_round()
        wall = time.perf_counter() - t0
        if sentinel.total != before:
            return fail("feeding the incident plane dispatched XLA compiles")
    if wall > FEED_BUDGET_S:
        return fail(f"{FEED_ROUNDS} monitor rounds took {wall:.2f}s "
                    f"(budget {FEED_BUDGET_S}s)")

    print(f"incident-smoke OK: timeline={merged['records']} records across "
          f"{len(merged['hosts'])} host(s), {FEED_ROUNDS} monitor rounds in "
          f"{wall * 1e3:.0f}ms, 0 compiles, artifacts in {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
