#!/usr/bin/env python
"""ragged-layout smoke: the ops/ragged.py CI contract (and
``make ragged-smoke``).

Runs the long-tail shape through ``layout="ragged"`` on CPU and asserts
the ragged subsystem's three promises:

* **byte equality, kernel-first** — the Pallas kernel under
  ``interpret=True`` (the TPU path's semantics, minus Mosaic) and the lax
  pool walk both reproduce the padded apply field by field, and the
  ragged ``DocBatch`` merge / streaming session match the padded oracle
  end to end (spans, roots, patches, digest);
* **the buckets are gone** — the merge reports
  ``padding_efficiency == 1.0`` (trip counts are data: zero padded-op
  waste, where even the paged layout burns its pow-2 page buckets);
* **observable** — the ``peritext_ragged_*`` gauges render in the
  Prometheus exposition and ``devprof.snapshot()`` carries the
  ``ragged`` section (docs/pages walked, padded-slot waste 0).

Artifacts (``ragged-report.json``, a devprof snapshot, the Prometheus
exposition) are written for upload.  Exit nonzero on any violation — a
ragged regression fails CI like a correctness one.
"""

import argparse
import json
import os
import random
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=8)
    parser.add_argument("--out", default="ragged-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    import numpy as np

    import jax.numpy as jnp

    from peritext_tpu.api.batch import DocBatch
    from peritext_tpu.obs import GLOBAL_DEVPROF, prometheus_text
    from peritext_tpu.ops.encode import encode_doc_streams, pad_doc_streams
    from peritext_tpu.ops.kernel import apply_batch_jit, encoded_arrays_of
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.ops.ragged import (
        apply_batch_ragged_jit,
        plan_arrays,
        stream_counts,
    )
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.store.paged import PagedDocStore, group_stream_arrays
    from peritext_tpu.store.ragged import ragged_plan
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    report = {"seed": args.seed}

    # long-tail workload: a tweet fleet plus one essay
    tweets = generate_workload(seed=args.seed, num_docs=24, ops_per_doc=8)
    essay = generate_workload(seed=args.seed + 90_001, num_docs=1,
                              ops_per_doc=300)
    workloads = tweets + essay

    # -- kernel differential, BOTH impls (interpret = the TPU semantics) -----
    per_doc, fallback, actor_tables, attr_tables, map_tables = (
        encode_doc_streams(workloads)
    )
    enc = pad_doc_streams(
        per_doc, fallback, actor_tables, attr_tables, map_tables
    )
    d = enc.ins_ref.shape[0]
    ins_counts, del_counts = stream_counts(enc)
    oracle = apply_batch_jit(
        empty_docs(d, 512, 128), encoded_arrays_of(enc)
    )
    for impl in ("lax", "pallas_interpret"):
        store = PagedDocStore(d, 512, 128)
        rows = np.arange(d, dtype=np.int64)
        store.ensure_rows(rows, np.asarray(ins_counts, np.int64))
        store.pool_elem, store.pool_char, store.aux = apply_batch_ragged_jit(
            store.pool_elem, store.pool_char, store.aux,
            *plan_arrays(ragged_plan(store)),
            group_stream_arrays(enc, None, d),
            jnp.asarray(ins_counts), jnp.asarray(del_counts),
            ragged_impl=impl,
        )
        got = store.materialize_rows(rows, bucket_pages=store.max_doc_pages)
        for f in oracle._fields:
            a = np.asarray(getattr(oracle, f))
            b = np.asarray(getattr(got, f))
            if f in ("elem_id", "char"):
                b = b[:, : a.shape[1]]
            assert np.array_equal(a, b), f"ragged/{impl} diverges on {f}"
    report["kernel"] = {"docs": d, "impls": ["lax", "pallas_interpret"],
                        "byte_equal": True}
    print(f"ragged-smoke: kernel equal on {d} docs (lax + pallas interpret)")

    # -- batch byte equality + zero waste ------------------------------------
    GLOBAL_DEVPROF.reset()
    padded = DocBatch(slot_capacity=512, mark_capacity=128).merge(workloads)
    with GLOBAL_DEVPROF:
        ragged_batch = DocBatch(slot_capacity=512, mark_capacity=128,
                                layout="ragged")
        ragged = ragged_batch.merge(workloads)
    assert padded.spans == ragged.spans, "ragged batch diverged from padded"
    assert padded.roots == ragged.roots, "ragged roots diverged from padded"
    assert padded.fallback_docs == ragged.fallback_docs
    assert ragged.stats.padding_efficiency == 1.0, (
        "ragged layout reported padded-op waste; trip counts must be data"
    )
    report["batch"] = {
        "docs": len(workloads),
        "padding_efficiency_padded": padded.stats.padding_efficiency,
        "padding_efficiency_ragged": ragged.stats.padding_efficiency,
        "page_pool": ragged_batch.last_store.pool_stats(),
        "byte_equal": True,
    }
    print(f"ragged-smoke: batch equal; stream efficiency "
          f"{padded.stats.padding_efficiency:.3f} -> "
          f"{ragged.stats.padding_efficiency:.3f}")

    # -- streaming byte equality through the ragged drain ---------------------
    rng = random.Random(args.seed)
    arrival = []
    for w in workloads[:12]:
        chs = [ch for log in w.values() for ch in log]
        rng.shuffle(chs)
        half = max(1, len(chs) // 2)
        arrival.append([
            encode_frame(sorted(chs[:half], key=lambda c: (c.actor, c.seq))),
            encode_frame(sorted(chs[half:], key=lambda c: (c.actor, c.seq))),
        ])

    def build(layout):
        s = StreamingMerge(
            num_docs=len(arrival), actors=("doc1", "doc2", "doc3"),
            slot_capacity=512, mark_capacity=128, tomb_capacity=128,
            layout=layout,
        )
        for r in range(2):
            s.ingest_frames((d, b[r]) for d, b in enumerate(arrival))
            s.drain()
        return s

    sp = build("padded")
    with GLOBAL_DEVPROF:
        sq = build("ragged")
        dq = sq.digest()
    dp = sp.digest()
    assert dp == dq, f"digest diverged: padded {dp:#x} ragged {dq:#x}"
    assert sp.read_all() == sq.read_all(), "streaming spans diverged"
    assert sp.read_patches_all() == sq.read_patches_all(), "patches diverged"
    report["streaming"] = {
        "docs": len(arrival),
        "digest": f"{dq:#010x}",
        "rounds": sq.rounds,
        "page_pool": sq.store.pool_stats(),
        "byte_equal": True,
    }
    print(f"ragged-smoke: streaming equal (digest {dq:#010x}, "
          f"{sq.store.pool_stats()['pages_in_use']} pages in use)")

    # -- telemetry surfaces ---------------------------------------------------
    snap = GLOBAL_DEVPROF.snapshot()
    rg = snap["ragged"]
    assert rg is not None, "devprof ragged section missing"
    assert rg["padded_slot_waste"] == 0, "ragged padded-slot waste must be 0"
    assert rg["docs_walked"] > 0 and rg["pages_walked"] > 0
    text = prometheus_text(devprof=GLOBAL_DEVPROF, session=sq)
    for gauge in ("peritext_ragged_dispatches", "peritext_ragged_docs_walked",
                  "peritext_ragged_pages_walked",
                  "peritext_ragged_padded_slot_waste"):
        assert gauge in text, f"gauge {gauge} missing from exposition"
    report["telemetry"] = {"gauges": True, "devprof_ragged": rg}
    print("ragged-smoke: peritext_ragged_* gauges + devprof section OK")

    (out / "ragged-report.json").write_text(json.dumps(report, indent=2))
    (out / "devprof-snapshot.json").write_text(json.dumps(snap, indent=2))
    (out / "metrics.prom").write_text(text)
    print(f"ragged-smoke: PASS (artifacts in {out})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
