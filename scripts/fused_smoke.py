#!/usr/bin/env python
"""fused-pipeline smoke: the fused device-resident round pipeline's CI
contract (and ``make fused-smoke``).

Asserts, on CPU, the four promises ISSUE 9 makes:

* **byte equality** — the fused pipeline (staged multi-round programs,
  pipelined drain, staging lane, digest prefetch) is indistinguishable
  from the per-round dispatch discipline on the same workload: spans,
  incremental patches and full-state digests bit-equal, padded AND paged
  layouts, several fuzz seeds;
* **staging overlaps** — the double-buffered staging lane actually staged
  the drain's batches off the scheduling thread (lane counters), and the
  serialized (sync-per-drain) twin is no FASTER than the pipelined drain
  beyond noise — overlap never costs wall;
* **zero steady-state compiles** — a fresh session replaying the same
  workload shapes dispatches only already-compiled fused programs
  (RecompileSentinel);
* **observable** — devprof sees the fused dispatch sites
  (``apply_batch_staged_rounds``) and the fused-origin occupancy rows.

Artifacts (``fused-report.json``, a devprof snapshot) are written for
upload.  Exit nonzero on any violation.
"""

import argparse
import json
import os
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _session(layout, fused, static_rounds=False, num_docs=8):
    from peritext_tpu.parallel.streaming import StreamingMerge

    s = StreamingMerge(
        num_docs=num_docs, actors=("doc1", "doc2", "doc3"),
        slot_capacity=256, mark_capacity=96, tomb_capacity=128,
        round_insert_capacity=24, round_delete_capacity=12,
        round_mark_capacity=12, round_map_capacity=8,
        static_rounds=static_rounds, layout=layout,
    )
    s.fused_pipeline = fused
    s.prefetch_digest = fused
    return s


def _feed(s, workloads, seed, chunks=3, per_round=False, sync=False):
    """One seeded feed plan shared by every arm (fused, per-round oracle,
    lock-step serialized) — the equality assertions depend on all arms
    deriving the SAME frame plan.  ``sync`` blocks after each drain (the
    overlap smoke's serialized arm)."""
    from peritext_tpu.parallel.codec import encode_frame

    rng = random.Random(seed)
    plans = []
    for w in workloads:
        ch = [c for a in sorted(w) for c in w[a]]
        rng.shuffle(ch)
        size = -(-len(ch) // chunks)
        plans.append([ch[i:i + size] for i in range(0, len(ch), size)])
    t0 = time.perf_counter()
    for r in range(chunks):
        s.ingest_frames(
            (d, encode_frame(sorted(p[r], key=lambda c: (c.actor, c.seq))))
            for d, p in enumerate(plans) if r < len(p)
        )
        if per_round:
            while s.step() > 0:
                pass
        else:
            s.drain()
            if sync:
                s.sync_device()
    digest = s.digest()
    return time.perf_counter() - t0, digest


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seeds", type=int, nargs="*", default=[5, 19])
    parser.add_argument("--out", default="fused-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    from peritext_tpu.obs import GLOBAL_DEVPROF
    from peritext_tpu.observability import RecompileSentinel
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    report = {"seeds": args.seeds, "layouts": {}}

    GLOBAL_DEVPROF.reset()
    with GLOBAL_DEVPROF:
        # -- equivalence sweep: fused vs per-round, both layouts ------------
        for layout in ("padded", "paged"):
            rows = []
            for seed in args.seeds:
                wl = generate_workload(seed=seed, num_docs=8, ops_per_doc=48)
                fused = _session(layout, True)
                _, dg_f = _feed(fused, wl, seed)
                oracle = _session(layout, False)
                _, dg_o = _feed(oracle, wl, seed, per_round=True)
                assert dg_f == dg_o, (
                    f"{layout} seed {seed}: fused digest {dg_f:#x} != "
                    f"per-round {dg_o:#x}"
                )
                assert fused.read_all() == oracle.read_all(), (
                    f"{layout} seed {seed}: span sweep diverged")
                assert fused.read_patches_all() == oracle.read_patches_all(), (
                    f"{layout} seed {seed}: patch sweep diverged")
                assert fused.rounds == oracle.rounds
                rows.append({"seed": seed, "digest": dg_f,
                             "rounds": fused.rounds,
                             "stager": fused._stager.stats()
                             if fused._stager else None})
            report["layouts"][layout] = rows

        # -- staging-overlap smoke ------------------------------------------
        wl = generate_workload(seed=args.seeds[0], num_docs=8, ops_per_doc=48)
        pipelined = _session("padded", True)
        t_pipe, dg_a = _feed(pipelined, wl, args.seeds[0])
        lane = pipelined._stager.stats()
        assert lane["staged"] > 0, "the staging lane must have staged batches"
        assert lane["errors"] == 0, lane
        serial = _session("padded", True)
        serial.prefetch_digest = False
        # same feed plan, but lock-step: sync after every drain
        t_serial, dg_b = _feed(serial, wl, args.seeds[0], sync=True)
        assert dg_a == dg_b
        report["staging_overlap"] = {
            "pipelined_s": round(t_pipe, 4),
            "serialized_s": round(t_serial, 4),
            "lane": lane,
        }
        # overlap must never COST wall beyond run noise (2x guard: this is
        # a smoke direction check, not a perf gate — the ledger gates perf)
        assert t_pipe <= 2.0 * t_serial, report["staging_overlap"]

        # -- zero steady-state compiles -------------------------------------
        wl = generate_workload(seed=77, num_docs=6, ops_per_doc=40)
        cold = _session("padded", True, num_docs=6)
        _, dg_cold = _feed(cold, wl, 77)
        with RecompileSentinel() as sentinel:
            sentinel.mark()
            warm = _session("padded", True, num_docs=6)
            _, dg_warm = _feed(warm, wl, 77)
            sentinel.assert_steady_state("fused pipeline repeat workload")
        assert dg_warm == dg_cold
        report["steady_state_compiles"] = 0

    snap = GLOBAL_DEVPROF.snapshot()
    assert any(site.startswith("apply_batch_staged_rounds")
               for site in snap["sites"]), sorted(snap["sites"])
    assert any(o["origin"] == "streaming.fused"
               for o in snap["occupancy"].values()), "fused occupancy origin"
    report["devprof_sites"] = sorted(snap["sites"])

    (out / "fused-report.json").write_text(json.dumps(report, indent=2))
    (out / "devprof-snapshot.json").write_text(json.dumps(snap, indent=2))
    print(json.dumps({"ok": True,
                      "staging_overlap": report["staging_overlap"],
                      "layouts": {k: len(v)
                                  for k, v in report["layouts"].items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
