"""Generate the v4 preset deflate dictionary (wire option ``preset``).

Per-doc links pay a cold deflate window per link: the bench's per-doc-link
variant measured 6.17-6.99 B/op vs 5.27 for the host-link mux whose shared
window amortizes cross-frame redundancy (VERDICT r4 task 8).  A protocol
preset dictionary primes each fresh link's window with representative
UNCOMPRESSED v3 session-frame bodies so first frames back-reference it the
way later frames reference the live window — the zlib analog of Brotli's
built-in dictionary.

The corpus is deterministic (seeded fuzz workloads DISJOINT from every
bench seed, FIFO arrival, per-doc sessions), the tail 8 KiB of the
concatenated bodies (zlib uses the dictionary tail-first; 8 KiB measured
within 0.1% of the full 32 KiB window on bench shapes).  The output is a
PROTOCOL CONSTANT: peers must byte-match, so regenerating after codec or
generator changes is a wire-compat break — ship a new file + option epoch,
never silently overwrite.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

OUT = os.path.join(os.path.dirname(__file__), os.pardir,
                   "peritext_tpu", "parallel", "wire_preset.bin")
SIZE = 8192


def main():
    from bench import build_arrival
    from peritext_tpu.parallel.codec import WireSession
    from peritext_tpu.testing.fuzz import generate_workload

    train = generate_workload(seed=999, num_docs=4, ops_per_doc=192)
    arr, _ = build_arrival(train, 4, 999, as_frames=False,
                           arrival_model="fifo")
    bodies = []
    for doc_batches in arr:
        s = WireSession(compress=False)
        for b in doc_batches:
            bodies.append(
                s.encode_frame(sorted(b, key=lambda c: (c.actor, c.seq))))
    blob = b"".join(bodies)[-SIZE:]
    with open(OUT, "wb") as fh:
        fh.write(blob)
    print(f"wrote {len(blob)} bytes to {OUT}")


if __name__ == "__main__":
    main()
