"""Same-run A/B: unfused vs fused round replay (chip load swamps
cross-run absolutes, so variants interleave in ONE process and report
min-of-N each)."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def main(docs=2048, rounds=4, opd=192):
    import jax
    import jax.numpy as jnp

    from bench import build_arrival
    from peritext_tpu.ops.kernel import (
        apply_batch_compact_jit, apply_batch_compact_rounds_jit,
    )
    from peritext_tpu.ops.packed import empty_docs
    from peritext_tpu.parallel.streaming import (
        StreamingMerge, _resolve_block_digest_jit,
    )
    from peritext_tpu.testing.fuzz import generate_workload

    workloads = generate_workload(seed=0, num_docs=docs, ops_per_doc=opd)
    arrival, _ = build_arrival(workloads, rounds, 0)
    captured = []
    s = StreamingMerge(
        num_docs=docs, actors=("doc1", "doc2", "doc3"),
        slot_capacity=384, mark_capacity=96, tomb_capacity=384,
        round_insert_capacity=256, round_delete_capacity=128,
        round_mark_capacity=128,
    )
    s._capture_rounds = captured
    t0 = time.perf_counter()
    for r in range(rounds):
        s.ingest_frames((doc, b[r]) for doc, b in enumerate(arrival)
                        if r < len(b))
        s.drain()
    expected = s.digest()
    print(f"live session (capture on): {time.perf_counter()-t0:.2f}s, "
          f"{len(captured)} rounds captured")

    state0 = jax.device_put(
        empty_docs(s._padded_docs, 384, 96, tomb_capacity=384))
    staged = [
        ((tuple(jax.device_put(np.asarray(c)) for c in counts),
          ins, dels, mk, mp), widths, ls)
        for (counts, ins, dels, mk, mp), widths, ls in captured
    ]
    tables = s._digest_tables(0, s._padded_docs)
    row_mask = jnp.ones(s._padded_docs, bool)

    def digest_of(st):
        _, per_doc = _resolve_block_digest_jit(
            st, s.comment_capacity, row_mask, *tables)
        return int(np.asarray(per_doc).sum(dtype=np.uint32))

    def unfused():
        st = state0
        for (c, i, dl, mk, mp), w, ls in staged:
            st = apply_batch_compact_jit(st, c, i, dl, mk, mp, widths=w,
                                         insert_loop_slots=ls)
        return st

    def fused():
        return apply_batch_compact_rounds_jit(
            state0, [r[0] for r in staged],
            widths_seq=[r[1] for r in staged],
            loop_slots_seq=[r[2] for r in staged])

    assert digest_of(unfused()) == expected
    assert digest_of(fused()) == expected

    res = {"unfused": [], "fused": []}
    for _ in range(4):
        for name, fn in (("unfused", unfused), ("fused", fused)):
            t0 = time.perf_counter()
            dg = digest_of(fn())
            res[name].append(time.perf_counter() - t0)
            assert dg == expected
    for name, ts in res.items():
        print(f"{name}: min {min(ts)*1e3:7.1f} ms  all "
              f"{[round(t*1e3) for t in ts]}")

    # live session again, capture off (the fused drain path), same process
    t0 = time.perf_counter()
    s2 = StreamingMerge(
        num_docs=docs, actors=("doc1", "doc2", "doc3"),
        slot_capacity=384, mark_capacity=96, tomb_capacity=384,
        round_insert_capacity=256, round_delete_capacity=128,
        round_mark_capacity=128,
    )
    for r in range(rounds):
        s2.ingest_frames((doc, b[r]) for doc, b in enumerate(arrival)
                         if r < len(b))
        s2.drain()
    assert s2.digest() == expected
    print(f"live session (fused drain, warm compiles): "
          f"{time.perf_counter()-t0:.2f}s")


if __name__ == "__main__":
    main()
