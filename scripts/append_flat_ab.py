"""Same-run A/B: batch-dim scatter vs FLATTENED 1-D scatter for the
mark/tomb append phase (round 5 follow-up).

The vmapped scatter costs ~25 ns/element on the round-apply's mark phase
(apply_phase_cost.py).  Hypothesis: scattering into the flattened
(D*cap,) table with globally-unique indices (doc*cap + count + src)
lowers to a cheaper gather-scatter than the batched form.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np


def flat_append(table, count, rows, rows_count):
    """(D, cap) tables, (D,) count, (D, K) rows, (D,) rows_count —
    flattened single scatter."""
    import jax.numpy as jnp

    single = not isinstance(table, dict)
    tables = {"_": table} if single else table
    new_rows = {"_": rows} if single else rows
    t0 = next(iter(tables.values()))
    d, cap = t0.shape
    km = next(iter(new_rows.values())).shape[1]
    src = jnp.arange(km, dtype=jnp.int32)[None, :]
    dst_in = count[:, None] + src  # (D, K) in-table position
    valid = (src < rows_count[:, None]) & (dst_in < cap)
    base = (jnp.arange(d, dtype=jnp.int32) * cap)[:, None]
    flat_dst = jnp.where(valid, base + dst_in, d * cap).reshape(-1)
    out = {
        c: tables[c].reshape(-1).at[flat_dst].set(
            new_rows[c].reshape(-1), mode="drop").reshape(d, cap)
        for c in tables
    }
    overflow = count + rows_count > cap
    new_count = jnp.minimum(count + rows_count, cap)
    if single:
        return out["_"], new_count, overflow
    return out, new_count, overflow


def main():
    import jax
    import jax.numpy as jnp

    from peritext_tpu.ops import kernel

    docs, cap, km = 2048, 96, 128
    rng = np.random.default_rng(0)
    cols = [f"c{i}" for i in range(8)]
    table = {c: jax.device_put(jnp.asarray(rng.integers(0, 1000, (docs, cap)),
                                           jnp.int32)) for c in cols}
    rows = {c: jax.device_put(jnp.asarray(rng.integers(0, 1000, (docs, km)),
                                          jnp.int32)) for c in cols}
    count = jax.device_put(jnp.asarray(rng.integers(0, 16, docs), jnp.int32))
    rows_count = jax.device_put(
        jnp.asarray(rng.integers(0, km // 2, docs), jnp.int32))

    batched = jax.jit(jax.vmap(kernel._append_rows))
    flat = jax.jit(flat_append)

    o1 = batched(table, count, rows, rows_count)
    o2 = flat(table, count, rows, rows_count)
    for c in cols:
        np.testing.assert_array_equal(np.asarray(o1[0][c]),
                                      np.asarray(o2[0][c]))
    np.testing.assert_array_equal(np.asarray(o1[1]), np.asarray(o2[1]))
    np.testing.assert_array_equal(np.asarray(o1[2]), np.asarray(o2[2]))
    print("equivalent outputs ok")

    def steady(fn, reps=16):
        out = fn(table, count, rows, rows_count)
        np.asarray(out[1])
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(table, count, rows, rows_count)
        np.asarray(out[1])
        return (time.perf_counter() - t0) / reps

    for _ in range(2):
        for name, fn in (("batched", batched), ("flat", flat)):
            print(f"{name}: {steady(fn)*1e3:7.2f} ms")


if __name__ == "__main__":
    main()
