"""Host-side attribution for the sweep build (VERDICT r4 task 3).

cProfiles the build loop of the config-5b sweep at a reduced doc count so
the dominant host term is measured, not guessed.
Run:  python scripts/ingest_profile.py [docs]
"""
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main(d=16384):
    from bench import build_arrival  # noqa: F401  (import parity with bench)
    from peritext_tpu.api.batch import _oracle_doc
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    w = generate_workload(seed=200, num_docs=1, ops_per_doc=220)[0]
    changes = [ch for log in w.values() for ch in log]
    half = len(changes) // 2
    frames = [encode_frame(changes[:half]), encode_frame(changes[half:])]
    total_ops = sum(len(c.ops) for c in changes) * d

    sess = StreamingMerge(
        num_docs=d, actors=("doc1", "doc2", "doc3"),
        slot_capacity=512, mark_capacity=160, tomb_capacity=192,
        round_insert_capacity=192, round_delete_capacity=96,
        round_mark_capacity=96,
    )
    prof = cProfile.Profile()
    t0 = time.perf_counter()
    prof.enable()
    for frame in frames:
        sess.ingest_frames((doc, frame) for doc in range(d))
        sess.drain()
    prof.disable()
    wall = time.perf_counter() - t0
    print(f"docs={d} build={wall:.2f}s ops/s={total_ops / wall:,.0f}")
    s = io.StringIO()
    ps = pstats.Stats(prof, stream=s).sort_stats("cumulative")
    ps.print_stats(30)
    print(s.getvalue())


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 16384)
