#!/usr/bin/env python
"""paged-storage smoke: the store/ subsystem's CI contract (and
``make paged-smoke``).

Runs a small long-tail session through BOTH layouts on CPU and asserts the
paged subsystem's three promises:

* **byte equality** — a paged streaming session fed the same frames as a
  padded one produces identical spans, patches and full-state digests,
  and a paged ``DocBatch`` merge matches the padded merge doc-for-doc;
* **the waste goes away** — on the long-tail shape (one essay among
  tweets) the paged layout burns measurably less padded stream capacity
  than the padded layout (the full >= 5x gate lives in the
  ``batch_longdoc`` perf-ledger row; the smoke pins the direction);
* **observable** — the ``peritext_page_*`` gauges render in the
  Prometheus exposition, ``/devprof.json``'s snapshot carries the
  ``page_pool`` section, and ``health_snapshot`` composes it.

Artifacts (``paged-report.json``, a devprof snapshot, the Prometheus
exposition) are written for upload.  Exit nonzero on any violation — a
paged-storage regression fails CI like a correctness one.
"""

import argparse
import json
import os
import random
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=8)
    parser.add_argument("--out", default="paged-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    from peritext_tpu.api.batch import DocBatch
    from peritext_tpu.obs import GLOBAL_DEVPROF, health_snapshot, prometheus_text
    from peritext_tpu.parallel.codec import encode_frame
    from peritext_tpu.parallel.streaming import StreamingMerge
    from peritext_tpu.testing.fuzz import generate_workload

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    report = {"seed": args.seed}

    # long-tail workload: a tweet fleet plus one essay
    tweets = generate_workload(seed=args.seed, num_docs=24, ops_per_doc=8)
    essay = generate_workload(seed=args.seed + 90_001, num_docs=1,
                              ops_per_doc=300)
    workloads = tweets + essay

    # -- batch byte equality + waste direction -------------------------------
    padded = DocBatch(slot_capacity=512, mark_capacity=128).merge(workloads)
    paged_batch = DocBatch(slot_capacity=512, mark_capacity=128,
                           layout="paged")
    paged = paged_batch.merge(workloads)
    assert padded.spans == paged.spans, "paged batch diverged from padded"
    assert padded.roots == paged.roots, "paged roots diverged from padded"
    assert padded.fallback_docs == paged.fallback_docs
    assert paged.stats.padding_efficiency > padded.stats.padding_efficiency, (
        "paged layout did not improve stream occupancy on the long tail"
    )
    report["batch"] = {
        "docs": len(workloads),
        "padding_efficiency_padded": padded.stats.padding_efficiency,
        "padding_efficiency_paged": paged.stats.padding_efficiency,
        "page_pool": paged_batch.last_store.pool_stats(),
        "byte_equal": True,
    }
    print(f"paged-smoke: batch equal; stream efficiency "
          f"{padded.stats.padding_efficiency:.3f} -> "
          f"{paged.stats.padding_efficiency:.3f}")

    # -- streaming byte equality under the page pool --------------------------
    rng = random.Random(args.seed)
    arrival = []
    for w in workloads[:12]:
        chs = [ch for log in w.values() for ch in log]
        rng.shuffle(chs)
        half = max(1, len(chs) // 2)
        arrival.append([
            encode_frame(sorted(chs[:half], key=lambda c: (c.actor, c.seq))),
            encode_frame(sorted(chs[half:], key=lambda c: (c.actor, c.seq))),
        ])

    def build(layout):
        s = StreamingMerge(
            num_docs=len(arrival), actors=("doc1", "doc2", "doc3"),
            slot_capacity=512, mark_capacity=128, tomb_capacity=128,
            layout=layout,
        )
        for r in range(2):
            s.ingest_frames((d, b[r]) for d, b in enumerate(arrival))
            s.drain()
        return s

    GLOBAL_DEVPROF.reset()
    sp = build("padded")
    with GLOBAL_DEVPROF:
        sq = build("paged")
        dq = sq.digest()
    dp = sp.digest()
    assert dp == dq, f"digest diverged: padded {dp:#x} paged {dq:#x}"
    assert sp.read_all() == sq.read_all(), "streaming spans diverged"
    assert sp.read_patches_all() == sq.read_patches_all(), "patches diverged"
    report["streaming"] = {
        "docs": len(arrival),
        "digest": f"{dq:#010x}",
        "rounds": sq.rounds,
        "page_pool": sq.store.pool_stats(),
        "byte_equal": True,
    }
    print(f"paged-smoke: streaming equal (digest {dq:#010x}, "
          f"{sq.store.pool_stats()['pages_in_use']} pages in use)")

    # -- telemetry surfaces ---------------------------------------------------
    snap = GLOBAL_DEVPROF.snapshot()
    assert snap["page_pool"] is not None, "devprof page_pool section missing"
    assert any(
        o["origin"] == "streaming.paged" for o in snap["occupancy"].values()
    ), "paged occupancy rows missing"
    text = prometheus_text(devprof=GLOBAL_DEVPROF, session=sq)
    for gauge in ("peritext_page_pool_pages", "peritext_page_pages_in_use",
                  "peritext_page_pool_utilization",
                  "peritext_page_internal_frag_ratio"):
        assert gauge in text, f"gauge {gauge} missing from exposition"
    health = health_snapshot(session=sq, devprof=GLOBAL_DEVPROF)
    assert health["session"]["page_pool"]["pages_in_use"] > 0
    assert health["devprof"]["page_pool"] is not None
    report["telemetry"] = {
        "gauges": True,
        "devprof_page_pool": snap["page_pool"],
    }
    print("paged-smoke: peritext_page_* gauges + /devprof.json section OK")

    (out / "paged-report.json").write_text(json.dumps(report, indent=2))
    (out / "devprof-snapshot.json").write_text(json.dumps(snap, indent=2))
    (out / "metrics.prom").write_text(text)
    print(f"paged-smoke: PASS (artifacts in {out})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
