#!/usr/bin/env python
"""mesh-sharded host smoke: the doc-axis mesh's CI contract (and
``make mesh-smoke``).

Asserts, on 8 virtual CPU devices, the promises ISSUE 14 makes:

* **byte equality** — a drain on a 1/2/4/8-shard doc-axis mesh is
  indistinguishable from the single-device fused path: spans,
  incremental patches and full-state digests bit-equal across ALL three
  storage layouts (padded, paged, ragged), several fuzz seeds;
* **one staged program per drain batch** — the whole mesh commits as a
  single ``shard_map`` dispatch (``streaming.fused_dispatches`` delta);
* **zero steady-state compiles** — a fresh session replaying the same
  shapes on an equivalent mesh dispatches only already-compiled mesh
  programs (RecompileSentinel);
* **the collective reshard preserves bytes** — the sharded page pool's
  ICI ``reshard()`` moves pages over permute collectives without
  changing a single observable byte, and counts its moves;
* **observable** — devprof grows a ``mesh`` section (per-shard load /
  utilization, imbalance watermark) and the ``peritext_mesh_*`` gauges
  render in the Prometheus exposition.

Artifacts (``mesh-report.json``, the devprof snapshot, the gauge text)
are written for upload.  Exit nonzero on any violation.
"""

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

LAYOUTS = ("padded", "paged", "ragged")


def _mesh(n):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), ("docs",))


def _changes(workloads):
    return [[ch for log in w.values() for ch in log] for w in workloads]


def _replay(layout, mesh, changes, **kw):
    from peritext_tpu.parallel.streaming import StreamingMerge

    kw.setdefault("slot_capacity", 256)
    kw.setdefault("mark_capacity", 128)
    kw.setdefault("tomb_capacity", 128)
    sess = StreamingMerge(
        num_docs=len(changes), actors=("doc1", "doc2", "doc3"),
        layout=layout, mesh=mesh, **kw,
    )
    for doc, log in enumerate(changes):
        sess.ingest(doc, log)
    sess.drain()
    return sess


def _snapshot(sess):
    # read_patches_all consumes the patch stream: capture once per session
    return sess.digest(), sess.read_all(), sess.read_patches_all()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seeds", type=int, nargs="*", default=[3, 21])
    parser.add_argument("--out", default="mesh-artifacts",
                        help="artifact directory")
    args = parser.parse_args()

    import jax

    from peritext_tpu.obs import GLOBAL_COUNTERS, GLOBAL_DEVPROF
    from peritext_tpu.obs.exporters import prometheus_text
    from peritext_tpu.observability import RecompileSentinel
    from peritext_tpu.testing.fuzz import generate_workload

    devices = jax.devices()
    assert len(devices) >= 8, (
        f"mesh smoke needs 8 virtual devices, got {len(devices)} "
        "(set XLA_FLAGS=--xla_force_host_platform_device_count=8)"
    )
    shard_counts = (1, 2, 4, 8)

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    report = {"seeds": args.seeds, "shard_counts": list(shard_counts),
              "layouts": {}}

    GLOBAL_DEVPROF.reset()
    with GLOBAL_DEVPROF:
        # -- equality sweep: every layout x shard count vs single-device ----
        for layout in LAYOUTS:
            rows = []
            for seed in args.seeds:
                changes = _changes(
                    generate_workload(seed, num_docs=16, ops_per_doc=40)
                )
                digest, spans, patches = _snapshot(
                    _replay(layout, None, changes)
                )
                for n in shard_counts:
                    d0 = GLOBAL_COUNTERS.get("streaming.fused_dispatches")
                    sess = _replay(layout, _mesh(n), changes)
                    dispatches = (
                        GLOBAL_COUNTERS.get("streaming.fused_dispatches") - d0
                    )
                    tag = f"{layout} seed {seed} shards {n}"
                    assert sess.digest() == digest, f"{tag}: digest diverged"
                    assert sess.read_all() == spans, f"{tag}: spans diverged"
                    assert sess.read_patches_all() == patches, (
                        f"{tag}: patches diverged"
                    )
                    assert dispatches == 1, (
                        f"{tag}: drain batch took {dispatches} staged "
                        "programs, the mesh contract is ONE"
                    )
                    rows.append({"seed": seed, "shards": n,
                                 "digest": digest,
                                 "fused_dispatches": dispatches,
                                 "mesh": sess._mesh_stats() if n > 1 else None})
            report["layouts"][layout] = rows

        # -- zero steady-state compiles on an equivalent mesh ---------------
        changes = _changes(
            generate_workload(seed=45, num_docs=16, ops_per_doc=32)
        )
        for layout in LAYOUTS:
            _replay(layout, _mesh(8), changes)  # cold: pays the compiles
        with RecompileSentinel() as sentinel:
            sentinel.mark()
            for layout in LAYOUTS:
                _replay(layout, _mesh(8), changes)
            sentinel.assert_steady_state("fresh-session mesh replay")
        report["steady_state_compiles"] = 0

        # -- the sharded pool's collective reshard --------------------------
        changes = _changes(
            generate_workload(seed=77, num_docs=16, ops_per_doc=40)
        )
        digest, spans, patches = _snapshot(_replay("paged", None, changes))
        sess = _replay("paged", _mesh(4), changes)
        before = GLOBAL_COUNTERS.get("store.ici_page_moves")
        sess.reshard()
        assert sess.digest() == digest, "post-reshard digest diverged"
        assert sess.read_all() == spans, "post-reshard spans diverged"
        assert sess.read_patches_all() == patches, "post-reshard patches"
        moved = GLOBAL_COUNTERS.get("store.ici_page_moves") - before
        stats = sess._store.shard_stats()
        report["reshard"] = {"ici_page_moves": moved,
                             "shard_stats": stats,
                             "equality": "byte-identical"}

    # -- the observability surface ------------------------------------------
    snap = GLOBAL_DEVPROF.snapshot()
    assert snap["mesh"] is not None, "devprof mesh section never populated"
    assert snap["mesh"]["shards"] >= 2, snap["mesh"]
    gauges = prometheus_text(devprof=GLOBAL_DEVPROF)
    for metric in ("peritext_mesh_shards", "peritext_mesh_shard_load",
                   "peritext_mesh_shard_imbalance_ratio",
                   "peritext_mesh_peak_imbalance_ratio"):
        assert f"# TYPE {metric} gauge" in gauges, f"{metric} gauge missing"
    report["devprof_mesh"] = snap["mesh"]

    (out / "mesh-report.json").write_text(json.dumps(report, indent=2))
    (out / "devprof-snapshot.json").write_text(json.dumps(snap, indent=2))
    (out / "mesh-gauges.prom").write_text(gauges)
    print(json.dumps({"ok": True,
                      "reshard": report["reshard"]["ici_page_moves"],
                      "mesh": report["devprof_mesh"],
                      "layouts": {k: len(v)
                                  for k, v in report["layouts"].items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
