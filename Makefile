# Developer entry points (the reference's package.json scripts analog).
# Tests and dryruns run on CPU with 8 virtual devices; bench targets the
# real TPU when one is attached.

PY ?= python
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test fuzz fuzz-differential fuzz-frames fuzz-crash chaos weak-scaling \
	bench bench-smoke bench-streaming bench-fused entry dryrun lint lint-baseline \
	clean obs fleet perf-gate serve-smoke bench-serve paged-smoke bench-longdoc \
	fused-smoke fleet-serve-smoke bench-fleet-serve bench-markheavy \
	ragged-smoke plan-smoke bench-serve-fused mesh-smoke bench-mesh \
	latency-smoke incident-smoke history-smoke

test:
	$(PY) -m pytest tests/ -x -q

fuzz:
	$(CPU_ENV) $(PY) -m peritext_tpu.testing.fuzz

# device path vs scalar oracle (spans + cursors)
fuzz-differential:
	$(CPU_ENV) $(PY) -m peritext_tpu.testing.fuzz --differential

# crash-consistency: checkpoint mid-stream, kill, restore, repair
fuzz-crash:
	$(CPU_ENV) $(PY) -m peritext_tpu.testing.fuzz --crash-restore

# composed-fault chaos soak: delivery + corruption + peer stalls + injected
# device-round failures + crash-restore vs the byte-equality oracle
chaos:
	$(CPU_ENV) $(PY) scripts/chaos_soak.py --seeds 20

# 1/2/4/8-device virtual-mesh scaling + digest-invariance evidence
weak-scaling:
	$(PY) scripts/weak_scaling.py

# observability smoke (mirrors the CI obs-smoke job): 128-doc streaming
# session with tracing on; asserts a non-empty Perfetto dump parses and
# prints the per-stage summary (artifacts land in /tmp/pt-obs)
obs:
	$(CPU_ENV) $(PY) scripts/obs_smoke.py --out /tmp/pt-obs

# fleet convergence smoke (mirrors the CI fleet-smoke job): an in-process
# multi-host partition/heal episode — asymmetric partition, flapping + slow
# links, lag-ordered gossip heal, fleet-wide digest equality — plus the
# seeded divergence injection (artifacts land in /tmp/pt-fleet)
fleet:
	$(CPU_ENV) $(PY) scripts/fleet_smoke.py --out /tmp/pt-fleet

# serving-tier smoke (mirrors the CI serve-smoke job): overload burst ->
# typed shed verdicts + bounded queue, redelivery -> byte equality, and
# the `obs serve` health-check contract (exit 1 overloaded / 0 healthy);
# artifacts land in /tmp/pt-serve
serve-smoke:
	$(CPU_ENV) $(PY) scripts/serve_smoke.py --out /tmp/pt-serve

# time-to-visibility latency-plane smoke (mirrors the CI obs-smoke job's
# latency step): an armed serve session -> sum-consistent stage records +
# /latency.json + peritext_latency_* families, the `obs why` exit
# contract (0 clean / 1 regressed / 2 unreadable), and the <2% arming
# overhead pin (artifacts land in /tmp/pt-latency)
latency-smoke:
	$(CPU_ENV) $(PY) scripts/latency_smoke.py --out /tmp/pt-latency

# fleet incident-plane smoke (mirrors the CI incident-smoke job): the
# host-kill chaos episode must open EXACTLY a host-death incident and
# resolve it post-heal with time-to-detection reported, the per-host
# flight dumps merge into one cross-host timeline, the `obs incidents`
# / `obs status` / `obs flight` exit contracts hold, and feeding the
# plane compiles ZERO XLA programs (artifacts land in /tmp/pt-incident)
incident-smoke:
	$(CPU_ENV) $(PY) scripts/incident_smoke.py --out /tmp/pt-incident

# fleet history-plane smoke (mirrors the CI history-smoke job): an armed
# serve session retains frames, rolls JSONL segments over, and replays
# them byte-identically with ZERO XLA compiles; the serve-overload chaos
# episode scores as an anomaly no later than its incident opens; the
# `obs history` exit contract (0/1/2) holds; and the history-weighted
# `obs plan` replay is deterministic (artifacts land in /tmp/pt-history)
history-smoke:
	$(CPU_ENV) $(PY) scripts/history_smoke.py --out /tmp/pt-history

# sustained open-loop serving ladder: docs/s at the p99 apply-latency SLO
bench-serve:
	$(PY) bench.py --mode serve

# paged-storage smoke (mirrors the CI paged-smoke job): small long-tail
# session through both layouts — byte equality (spans/patches/digests),
# occupancy improvement direction, peritext_page_* gauges + /devprof.json
# page_pool section (artifacts land in /tmp/pt-paged)
paged-smoke:
	$(CPU_ENV) $(PY) scripts/paged_smoke.py --out /tmp/pt-paged

# ragged-layout smoke (mirrors the CI ragged-smoke job): the Pallas kernel
# in interpret mode + the lax pool walk against the padded oracle, the
# ragged DocBatch/streaming byte equality, padding_efficiency == 1.0, and
# the peritext_ragged_* gauges (artifacts land in /tmp/pt-ragged)
ragged-smoke:
	$(CPU_ENV) $(PY) scripts/ragged_smoke.py --out /tmp/pt-ragged

# long-tail layout comparison row: one essay among a tweet fleet, all
# three layouts measured, byte equality asserted, waste ratio reported
bench-longdoc:
	$(PY) bench.py --mode longdoc

# fused-pipeline smoke (mirrors the CI fused-smoke job): fused vs
# per-round byte equality across both layouts, staging-overlap direction,
# zero steady-state compiles, fused devprof sites (artifacts in
# /tmp/pt-fused)
fused-smoke:
	$(CPU_ENV) $(PY) scripts/fused_smoke.py --out /tmp/pt-fused

# fleet-serve smoke (mirrors the CI fleet-serve-smoke job): a 3-host
# FleetFrontend under round-robin traffic, one serving host killed
# mid-traffic — lease death detection, checkpoint+journal failover,
# typed verdicts only, acked-op survival, post-heal byte equality, and
# the /fleet.json + peritext_fleet_* exporter surface (artifacts land
# in /tmp/pt-fleet-serve)
fleet-serve-smoke:
	$(CPU_ENV) $(PY) scripts/fleet_serve_smoke.py --out /tmp/pt-fleet-serve

# host-kill failover episode as a measurement: fleet frames applied/s
# with every failover oracle asserted in-row
bench-fleet-serve:
	$(PY) bench.py --mode fleet-serve

# device-as-OS planner smoke (mirrors the CI plan-smoke job): 32 tenants
# fuse into one staged dispatch per window (byte equality vs per-session
# drains), then the closed-loop planner proposes statics from the captured
# devprof snapshot and the proposal replays through the bench row
plan-smoke:
	$(CPU_ENV) $(PY) scripts/plan_smoke.py --out /tmp/pt-plan

# multi-tenant fused-dispatch row: N small tenants on one lane vs
# per-session drains (dispatch amortization; byte equality in-row)
bench-serve-fused:
	$(PY) bench.py --mode serve-fused

# mesh-sharded host smoke (mirrors the CI mesh-smoke job): 1/2/4/8-shard
# doc-axis drains byte-equal to single-device across all three layouts,
# one shard_map program per drain batch, zero steady-state compiles, the
# collective reshard byte-preserving, peritext_mesh_* gauges rendered
# (artifacts land in /tmp/pt-mesh)
mesh-smoke:
	$(CPU_ENV) $(PY) scripts/mesh_smoke.py --out /tmp/pt-mesh

# sustained mesh drain throughput: the 1/2/4/8-shard rung sweep with byte
# equality and the one-dispatch contract asserted in-row
bench-mesh:
	$(PY) bench.py --mode mesh

# mark-heavy editorial pass (span-overlap explosion) vs the scalar oracle
bench-markheavy:
	$(PY) bench.py --mode markheavy

# streaming frame ingest vs oracle (spans + incremental patch streams)
fuzz-frames:
	$(CPU_ENV) $(PY) -m peritext_tpu.testing.fuzz --differential-frames

bench:
	$(PY) bench.py

bench-smoke:
	$(PY) bench.py --smoke

bench-streaming:
	$(PY) bench.py --mode streaming

# fused device-resident round pipeline vs per-round dispatch (same
# workload, byte equality asserted in-row on every measured seed)
bench-fused:
	$(PY) bench.py --mode streaming-fused

bench-engine:  # device-only streaming replay: the engine limit vs the link
	$(PY) bench.py --mode engine

# perf-regression gate (mirrors the CI perf-gate job): CPU mini-ladder with
# devprof sampling appended to a scratch copy of the committed reference
# ledger, then gated with per-row tolerance bands (exit 1 on regression)
perf-gate:
	cp perf/reference_ledger.jsonl /tmp/pt-perf-gate.jsonl
	PT_BENCH_LADDER_ROWS="streaming,streaming_fused,wire,serve_sustained,serve_multitenant,batch_longdoc,batch_8k_ragged,markheavy,fleet_serve,serve_mesh_sustained" $(PY) bench.py \
		--mode ladder --smoke --platform cpu --devprof \
		--ledger /tmp/pt-perf-gate.jsonl
	$(PY) -m peritext_tpu.obs perf /tmp/pt-perf-gate.jsonl --gate

entry:
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; fn, a = g.entry(); \
	jax.block_until_ready(jax.jit(fn)(*a)); print('entry OK')"

dryrun:
	$(CPU_ENV) $(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; g.dryrun_multichip(8)"

# Lint = syntax floor (compileall) + graftlint, the project's determinism &
# tracer-safety suite (rules PTL001-PTL006; see DESIGN.md "Determinism
# contract").  Known intentional violations are attributed in
# graftlint_baseline.json; anything new fails here and in CI.
# CI additionally runs ruff with the config in pyproject.toml.
lint:
	$(PY) -m compileall -q peritext_tpu tests demos scripts bench.py __graft_entry__.py
	$(PY) -m peritext_tpu.analysis peritext_tpu

# regenerate the graftlint baseline (justify any new TODO entries by hand)
lint-baseline:
	$(PY) -m peritext_tpu.analysis peritext_tpu --update-baseline --baseline graftlint_baseline.json

clean:
	rm -rf peritext_tpu/native/_build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
